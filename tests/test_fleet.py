"""Fleet tests: shard-plan determinism, scheduler lifecycle, merge.

The elastic fleet (galah_tpu/fleet/) runs one dereplication across
preemptible worker subprocesses and must converge byte-identically to
a single-process run. The full kill/resume proof lives in the chaos
harness (scripts/chaos_run.py --workload fleet); this file covers the
deterministic building blocks in-process with fake workers:

  * plan.py — byte-identical shard specs for identical inputs, and a
    --resume against a mismatched plan refuses, NAMING the field;
  * scheduler.py — fake workers driven to done, exit-75 reassignment,
    retry-budget quarantine, and event-log replay adopting a prior
    (killed) scheduler's attempts;
  * merge.py — shard-local caches remapped to global indices, a
    cross-shard pair changing the outcome, replay producing the
    engine's cluster shape;
  * obs/heartbeat.read_latest_beat — the scheduler's liveness probe
    never raises on missing/torn/garbage files;
  * resilience/interrupt — the second signal forwards SIGTERM to
    registered worker process groups before the hard exit 75.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from galah_tpu.fleet import merge as fleet_merge
from galah_tpu.fleet import plan as fleet_plan
from galah_tpu.fleet import scheduler as fleet_scheduler
from galah_tpu.fleet.plan import build_plan, ensure_plan, save_plan
from galah_tpu.fleet.scheduler import FleetScheduler
from galah_tpu.io import atomic
from galah_tpu.obs.heartbeat import read_latest_beat
from galah_tpu.resilience.policy import RetryPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- plan ------------------------------------------------------------


def test_build_plan_contiguous_balanced():
    genomes = [f"g{i}.fna" for i in range(10)]
    shards = build_plan(genomes, 3)
    assert [(s.lo, s.hi) for s in shards] == [(0, 4), (4, 7), (7, 10)]
    assert [s.shard_id for s in shards] == [0, 1, 2]
    for s in shards:
        assert list(s.genomes) == genomes[s.lo:s.hi]
    sizes = [s.hi - s.lo for s in shards]
    assert max(sizes) - min(sizes) <= 1


def test_build_plan_drops_empty_shards():
    shards = build_plan(["a.fna", "b.fna"], 5)
    assert [(s.lo, s.hi) for s in shards] == [(0, 1), (1, 2)]


def test_plan_file_bytes_deterministic(tmp_path):
    genomes = [f"/data/g{i}.fna" for i in range(7)]
    fields = {"ani": 95.0, "n_shards": 3}
    blobs = []
    for d in ("a", "b"):
        fleet_dir = str(tmp_path / d)
        os.makedirs(fleet_dir)
        save_plan(fleet_dir, fields, build_plan(genomes, 3))
        with open(fleet_plan.plan_path(fleet_dir), "rb") as f:
            blobs.append(f.read())
    assert blobs[0] == blobs[1]


def test_ensure_plan_roundtrip_is_stable(tmp_path):
    fleet_dir = str(tmp_path)
    genomes = [f"g{i}.fna" for i in range(5)]
    fields = {"ani": 95.0}
    first = ensure_plan(fleet_dir, genomes, fields, 2)
    with open(fleet_plan.plan_path(fleet_dir), "rb") as f:
        blob = f.read()
    again = ensure_plan(fleet_dir, genomes, fields, 2,
                        require_match=True)
    assert again == first
    with open(fleet_plan.plan_path(fleet_dir), "rb") as f:
        assert f.read() == blob  # loaded, not rewritten


def test_ensure_plan_resume_mismatch_names_the_field(tmp_path):
    fleet_dir = str(tmp_path)
    genomes = [f"g{i}.fna" for i in range(5)]
    ensure_plan(fleet_dir, genomes, {"ani": 95.0}, 2)
    with pytest.raises(ValueError, match="mismatched fields.*ani"):
        ensure_plan(fleet_dir, genomes, {"ani": 99.0}, 2,
                    require_match=True)
    with pytest.raises(ValueError, match="mismatched fields.*n_shards"):
        ensure_plan(fleet_dir, genomes, {"ani": 95.0}, 3,
                    require_match=True)


def test_ensure_plan_fresh_run_rebuilds_on_mismatch(tmp_path):
    fleet_dir = str(tmp_path)
    genomes = [f"g{i}.fna" for i in range(6)]
    ensure_plan(fleet_dir, genomes, {"ani": 95.0}, 2)
    # a stale event log from the superseded configuration must go too
    atomic.append_jsonl(fleet_plan.events_path(fleet_dir),
                        {"ev": "shard-launched", "shard": 0})
    shards = ensure_plan(fleet_dir, genomes, {"ani": 99.0}, 3)
    assert len(shards) == 3
    assert not os.path.exists(fleet_plan.events_path(fleet_dir))
    doc = fleet_plan.load_plan(fleet_dir)
    assert doc["fields"]["ani"] == 99.0


def test_fleet_run_resume_mismatch_exits_1(tmp_path, capsys):
    """CLI-level satellite: `fleet run --resume` against a plan from a
    different configuration exits 1 and names the mismatched field."""
    from galah_tpu.cli import main

    fleet_dir = str(tmp_path / "fleet")
    os.makedirs(fleet_dir)
    genomes = []
    for i in range(2):
        p = str(tmp_path / f"g{i}.fna")
        with open(p, "w") as f:
            f.write(">c1\n" + "ACGT" * 50 + "\n")
        genomes.append(p)
    save_plan(fleet_dir, {"ani": "something-else"},
              build_plan(genomes, 2))
    rc = main(["fleet", "--platform", "cpu", "run",
               "--genome-fasta-files", *genomes,
               "--precluster-method", "skani",
               "--cluster-method", "skani",
               "--fleet-dir", fleet_dir, "--resume",
               "--output-cluster-definition",
               str(tmp_path / "clusters.tsv")])
    assert rc == 1
    assert "mismatched fields" in capsys.readouterr().err


def test_fleet_run_refuses_non_skani_methods(tmp_path, capsys):
    from galah_tpu.cli import main

    p = str(tmp_path / "g0.fna")
    with open(p, "w") as f:
        f.write(">c1\n" + "ACGT" * 50 + "\n")
    rc = main(["fleet", "--platform", "cpu", "run",
               "--genome-fasta-files", p,
               "--precluster-method", "finch",
               "--cluster-method", "skani",
               "--fleet-dir", str(tmp_path / "fleet"),
               "--output-cluster-definition",
               str(tmp_path / "clusters.tsv")])
    assert rc == 1
    assert "fleet run requires" in capsys.readouterr().err


# -- scheduler (fake workers) ----------------------------------------


def _done_worker_argv(fleet_dir):
    """A fake worker that just leaves the merge artifact and exits 0."""
    def argv(spec, resume):
        path = fleet_scheduler.shard_distances_path(fleet_dir,
                                                    spec.shard_id)
        code = (f"import os; p = {path!r};"
                "os.makedirs(os.path.dirname(p), exist_ok=True);"
                "open(p, 'wb').write(b'npz')")
        return [sys.executable, "-c", code]
    return argv


def _fast_policy(max_attempts=3):
    return RetryPolicy(max_attempts=max_attempts, base_delay=0.01,
                       max_delay=0.02, jitter=0.0, seed=0)


def test_scheduler_drives_fake_workers_to_done(tmp_path):
    fleet_dir = str(tmp_path)
    shards = build_plan([f"g{i}.fna" for i in range(6)], 3)
    sched = FleetScheduler(fleet_dir, shards,
                           _done_worker_argv(fleet_dir), workers=2,
                           poll_s=0.02, heartbeat_s=0,
                           policy=_fast_policy())
    snap = sched.run()
    assert snap["shards_done"] == 3
    assert snap["shards_failed"] == 0
    assert snap["preemptions"] == 0
    assert [s["attempts"] for s in snap["shards"]] == [1, 1, 1]
    events = [r["ev"] for r in
              atomic.read_jsonl(fleet_plan.events_path(fleet_dir))[0]]
    assert events.count("shard-launched") == 3
    assert events.count("shard-done") == 3


def test_scheduler_reassigns_after_exit_75(tmp_path):
    fleet_dir = str(tmp_path)
    shards = build_plan([f"g{i}.fna" for i in range(4)], 2)

    def argv(spec, resume):
        path = fleet_scheduler.shard_distances_path(fleet_dir,
                                                    spec.shard_id)
        marker = os.path.join(fleet_dir, f"seen_{spec.shard_id}")
        code = textwrap.dedent(f"""
            import os, sys
            if not os.path.exists({marker!r}):
                open({marker!r}, 'w').close()
                sys.exit(75)
            p = {path!r}
            os.makedirs(os.path.dirname(p), exist_ok=True)
            open(p, 'wb').write(b'npz')
        """)
        return [sys.executable, "-c", code]

    sched = FleetScheduler(fleet_dir, shards, argv, workers=2,
                           poll_s=0.02, heartbeat_s=0,
                           policy=_fast_policy())
    snap = sched.run()
    assert snap["shards_done"] == 2
    assert snap["preemptions"] == 2
    assert snap["reassignments"] == 2
    for s in snap["shards"]:
        assert s["attempts"] == 2
        assert s["preemptions"] == ["exit-75"]


def test_scheduler_quarantines_on_exhausted_budget(tmp_path):
    fleet_dir = str(tmp_path)
    shards = build_plan(["g0.fna", "g1.fna"], 1)

    def argv(spec, resume):
        return [sys.executable, "-c", "import sys; sys.exit(3)"]

    sched = FleetScheduler(fleet_dir, shards, argv, workers=1,
                           poll_s=0.02, heartbeat_s=0,
                           policy=_fast_policy(max_attempts=2))
    snap = sched.run()
    assert snap["shards_done"] == 0
    assert snap["shards_failed"] == 1
    assert snap["shards"][0]["status"] == "failed"
    assert snap["shards"][0]["preemptions"] == ["exit-3", "exit-3"]
    events = [r["ev"] for r in
              atomic.read_jsonl(fleet_plan.events_path(fleet_dir))[0]]
    assert "fleet-shard-failed" in events


def test_scheduler_replays_prior_event_log(tmp_path):
    """A resumed scheduler adopts a killed predecessor's attempts: the
    pre-act shard-launched record with no matching completion becomes
    an uncharged 'orphaned' preemption, and lifetime attempt counts
    carry across the restart."""
    fleet_dir = str(tmp_path)
    shards = build_plan([f"g{i}.fna" for i in range(4)], 2)
    for sid in (0, 1):
        atomic.append_jsonl(
            fleet_plan.events_path(fleet_dir),
            {"ev": "shard-launched", "shard": sid, "pid": -1,
             "attempt": 1})
    sched = FleetScheduler(fleet_dir, shards,
                           _done_worker_argv(fleet_dir), workers=2,
                           poll_s=0.02, heartbeat_s=0,
                           policy=_fast_policy())
    snap = sched.run()
    assert snap["resumed"] is True
    assert snap["shards_done"] == 2
    for s in snap["shards"]:
        assert s["attempts"] == 2  # replayed launch + the real one
        assert s["preemptions"] == ["orphaned"]
    # 'orphaned' never charges the retry budget
    assert snap["retry_spend_s"] == 0.0


def test_stale_probe_ignores_prior_attempt_beats(tmp_path):
    """heartbeat.jsonl can survive a killed attempt; a resumed
    scheduler must clock staleness from the CURRENT attempt's launch,
    not the dead attempt's last beat, or every relaunched worker is
    stale-killed on the first poll tick before its first beat."""
    fleet_dir = str(tmp_path)
    shards = build_plan(["g0.fna", "g1.fna"], 1)
    sched = FleetScheduler(fleet_dir, shards,
                           _done_worker_argv(fleet_dir), workers=1,
                           poll_s=0.02, heartbeat_s=1, stale_s=30,
                           policy=_fast_policy())
    hb = fleet_scheduler.shard_heartbeat_path(fleet_dir, 0)
    os.makedirs(os.path.dirname(hb), exist_ok=True)
    atomic.append_jsonl(hb, {"beat": 7, "ts": 1.0})  # ancient beat

    class _StillRunning:
        def poll(self):
            return None

    rt = sched.shards[0]
    rt.proc = _StillRunning()
    rt.pgid = None
    rt.status = "running"
    rt.launched_wall = time.time()
    sched._poll_one(rt)
    assert rt.status == "running"
    assert sched.preemptions == 0
    # the probe still fires once the CURRENT attempt has gone quiet
    rt.launched_wall = time.time() - 3600
    sched._poll_one(rt)
    assert rt.status == "pending"
    assert rt.preemptions == ["stale-heartbeat"]


def test_launch_drops_prior_attempt_heartbeat(tmp_path):
    fleet_dir = str(tmp_path)
    shards = build_plan(["g0.fna"], 1)
    sched = FleetScheduler(fleet_dir, shards,
                           _done_worker_argv(fleet_dir), workers=1,
                           poll_s=0.02, heartbeat_s=0,
                           policy=_fast_policy())
    hb = fleet_scheduler.shard_heartbeat_path(fleet_dir, 0)
    os.makedirs(os.path.dirname(hb), exist_ok=True)
    atomic.append_jsonl(hb, {"beat": 1, "ts": 1.0})
    rt = sched.shards[0]
    sched._launch(rt)
    try:
        assert not os.path.exists(hb)
    finally:
        rt.proc.wait(timeout=10)
        fleet_scheduler.interrupt.unregister_worker_group(rt.pgid)


def test_is_our_worker_requires_env_stamp(tmp_path):
    """Orphan sweep must match the fleet's env stamp, never argv: a
    bystander whose cmdline names the shards dir (e.g. `galah-tpu top
    <fleet_dir>/shards/...`) is not ours and must not be killable."""
    fleet_dir = str(tmp_path)
    shards = build_plan(["g0.fna"], 1)
    sched = FleetScheduler(fleet_dir, shards,
                           _done_worker_argv(fleet_dir), workers=1,
                           poll_s=0.02, heartbeat_s=0,
                           policy=_fast_policy())
    shard_path = os.path.join(fleet_dir, "shards", "shard_000")
    clean_env = {k: v for k, v in os.environ.items()
                 if k != "GALAH_TPU_FLEET_WORKER"}
    # the ready line proves the child has exec'd: /proc/<pid>/environ
    # shows the PARENT's image until execve lands
    ready = "print('ready', flush=True); import time; time.sleep(60)"
    bystander = subprocess.Popen(
        [sys.executable, "-c", ready, shard_path, "galah_tpu"],
        env=clean_env, stdout=subprocess.PIPE)
    worker = subprocess.Popen(
        [sys.executable, "-c", ready],
        env=sched.base_env, stdout=subprocess.PIPE)
    try:
        bystander.stdout.readline()
        worker.stdout.readline()
        assert sched._is_our_worker(bystander.pid) is False
        assert sched._is_our_worker(worker.pid) is True
        assert sched._is_our_worker(2 ** 22 + 1234) is False  # gone
    finally:
        for p in (bystander, worker):
            p.kill()
            p.wait()
            p.stdout.close()


def test_fleet_run_rejects_zero_workers(tmp_path, capsys):
    """`--workers 0` is an error, not a silent fall-through to the
    env/default value (0 is falsy; only None means unset)."""
    from galah_tpu.cli import main

    p = str(tmp_path / "g0.fna")
    with open(p, "w") as f:
        f.write(">c1\n" + "ACGT" * 50 + "\n")
    rc = main(["fleet", "--platform", "cpu", "run",
               "--genome-fasta-files", p,
               "--precluster-method", "skani",
               "--cluster-method", "skani",
               "--workers", "0",
               "--fleet-dir", str(tmp_path / "fleet"),
               "--output-cluster-definition",
               str(tmp_path / "clusters.tsv")])
    assert rc == 1
    assert "--workers must be >= 1" in capsys.readouterr().err


# -- merge -----------------------------------------------------------


class _StubPreclusterer:
    """Hands merge.cross_shard_pairs a prebuilt cache and checks the
    keep-predicate really restricts it to cross-shard pairs."""

    def __init__(self, cross):
        self.cross = cross

    def distances_subset(self, genome_paths, keep):
        from galah_tpu.cluster.cache import PairDistanceCache

        cache = PairDistanceCache()
        for (i, j), v in self.cross.items():
            assert keep(i, j), (i, j)
            cache.insert((i, j), v)
        return cache


def _write_shard_npz(fleet_dir, shard_id, local_pairs):
    path = fleet_scheduler.shard_distances_path(fleet_dir, shard_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    keys = sorted(local_pairs)
    atomic.write_npz(path, {
        "ii": np.array([k[0] for k in keys], dtype=np.int64),
        "jj": np.array([k[1] for k in keys], dtype=np.int64),
        "vals": np.array([local_pairs[k] or 0.0 for k in keys],
                         dtype=np.float64),
        "has_val": np.array([local_pairs[k] is not None for k in keys],
                            dtype=bool),
    })


def test_load_shard_pairs_remaps_to_global(tmp_path):
    fleet_dir = str(tmp_path)
    shards = build_plan([f"g{i}.fna" for i in range(6)], 2)
    _write_shard_npz(fleet_dir, 0, {(0, 1): 99.0})
    _write_shard_npz(fleet_dir, 1, {(0, 2): 98.0, (1, 2): None})
    pairs = fleet_merge.load_shard_pairs(fleet_dir, shards)
    # shard 1 spans [3, 6): local (0, 2) is global (3, 5); the
    # has_val=False screen-miss is dropped, not merged as 0.0
    assert pairs == {(0, 1): 99.0, (3, 5): 98.0}


def test_merge_replays_cross_shard_join(tmp_path):
    fleet_dir = str(tmp_path)
    genomes = [f"g{i}.fna" for i in range(6)]
    shards = build_plan(genomes, 2)  # [0, 3) and [3, 6)
    _write_shard_npz(fleet_dir, 0, {(0, 1): 99.0, (0, 2): 98.5})
    _write_shard_npz(fleet_dir, 1, {(0, 1): 97.5, (0, 2): 99.2})
    # without the cross pair g3 founds shard 1's cluster; with it, g3
    # first joins rep 0 at 99.1 but is re-homed to the later rep g5
    # (ANI 99.2 beats 99.1, engine best-rep semantics), leaving g4 a
    # singleton — exactly the cross-shard rep/member flip that makes a
    # rep-only hierarchical merge unsafe
    clusters = fleet_merge.merge(fleet_dir, genomes, shards,
                                 _StubPreclusterer({(0, 3): 99.1}),
                                 95.0)
    assert clusters == [[0, 1, 2], [4], [5, 3]]
    without = fleet_merge.merge(fleet_dir, genomes, shards,
                                _StubPreclusterer({}), 95.0)
    assert without == [[0, 1, 2], [3, 4, 5]]


# -- heartbeat probe -------------------------------------------------


def test_read_latest_beat_missing_is_none(tmp_path):
    assert read_latest_beat(str(tmp_path)) is None
    assert read_latest_beat(str(tmp_path / "heartbeat.jsonl")) is None


def test_read_latest_beat_garbage_is_none(tmp_path):
    p = tmp_path / "heartbeat.jsonl"
    p.write_bytes(b"{half a record with no framing")
    assert read_latest_beat(str(p)) is None


def test_read_latest_beat_survives_torn_tail(tmp_path):
    p = str(tmp_path / "heartbeat.jsonl")
    atomic.append_jsonl(p, {"beat": 1, "ts": 10.0})
    atomic.append_jsonl(p, {"beat": 2, "ts": 11.0})
    with open(p, "ab") as f:
        f.write(b'{"beat": 3, "ts": 12.0')  # kill mid-append
    rec = read_latest_beat(p)
    assert rec == {"beat": 2, "ts": 11.0}
    # directory form resolves to the file the worker writes
    assert read_latest_beat(str(tmp_path)) == rec


# -- interrupt forwarding --------------------------------------------


def test_second_signal_forwards_sigterm_to_worker_groups():
    """The supervisor's hard exit must not leave its fleet running:
    signal #1 is cooperative, signal #2 forwards SIGTERM to every
    registered worker process group, then exits 75."""
    child_code = textwrap.dedent(f"""
        import os, subprocess, sys, time
        sys.path.insert(0, {REPO!r})
        from galah_tpu.resilience import interrupt
        interrupt.install()
        worker = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(120)"],
            start_new_session=True)
        interrupt.register_worker_group(worker.pid)
        print(worker.pid, flush=True)
        while True:
            time.sleep(0.05)
    """)
    proc = subprocess.Popen([sys.executable, "-c", child_code],
                            stdout=subprocess.PIPE)
    wpid = None
    try:
        wpid = int(proc.stdout.readline())
        proc.send_signal(signal.SIGTERM)
        time.sleep(0.3)  # let the cooperative first signal settle
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        proc.stdout.close()
    assert rc == 75
    deadline = time.monotonic() + 5
    alive = True
    while time.monotonic() < deadline:
        try:
            os.kill(wpid, 0)
        except ProcessLookupError:
            alive = False
            break
        time.sleep(0.05)
    if alive:  # don't leak the sleeper on failure
        os.kill(wpid, signal.SIGKILL)
    assert not alive, "worker survived the supervisor's hard exit"
