"""Fleet observability plane tests (obs/fleet_view, obs/openmetrics).

The cross-shard rollup's conservation contract (component blame
summing exactly to the fleet wall), its tolerance contract (torn
tails, shards deleted mid-aggregate, v6 shard reports), the ``top``
fleet grid, the ``fleet analyze`` CLI, heartbeat role/shard stamps,
the sharded perf-ledger key, and the OpenMetrics textfile exporter.
All jax-free: these run against synthetic fleet dirs on any host.
"""

from __future__ import annotations

import json
import os

import pytest

from galah_tpu import obs
from galah_tpu.fleet import plan as plan_mod
from galah_tpu.fleet import scheduler as sched_mod
from galah_tpu.io import atomic
from galah_tpu.obs import fleet_view
from galah_tpu.obs import heartbeat as obs_heartbeat
from galah_tpu.obs import ledger as ledger_mod
from galah_tpu.obs import metrics as obs_metrics
from galah_tpu.obs import openmetrics
from galah_tpu.obs import report as report_mod


@pytest.fixture(autouse=True)
def _clean_run_state():
    obs.reset_run()
    yield
    obs.reset_run()


def _stamp(fleet_dir, ev, ts, **fields):
    atomic.append_jsonl(plan_mod.events_path(fleet_dir),
                        {"ev": ev, "ts": ts, **fields},
                        site="fleet-events")


def _synthetic_fleet(tmp_path, n_shards=3):
    """A deterministic fleet timeline: shard 0 runs 0..6, shard 1 runs
    0..10 with a preemption + backoff at 4..4.5, shard 2 queues until
    2 and runs to 8; supervise ends at 10, merge takes 2 (wall 12)."""
    fleet_dir = str(tmp_path / "fleet")
    for sid in range(n_shards):
        os.makedirs(os.path.join(fleet_dir, "shards",
                                 f"shard_{sid:03d}"), exist_ok=True)
    _stamp(fleet_dir, "shard-launched", 0.0, shard=0, pid=-1)
    _stamp(fleet_dir, "shard-launched", 0.0, shard=1, pid=-1)
    _stamp(fleet_dir, "shard-preempted", 4.0, shard=1,
           reason="worker-exit")
    _stamp(fleet_dir, "shard-backoff", 4.0, shard=1, backoff_s=0.5)
    _stamp(fleet_dir, "shard-launched", 4.5, shard=1, pid=-1)
    _stamp(fleet_dir, "shard-launched", 2.0, shard=2, pid=-1)
    _stamp(fleet_dir, "shard-done", 6.0, shard=0)
    _stamp(fleet_dir, "shard-done", 8.0, shard=2)
    _stamp(fleet_dir, "shard-done", 10.0, shard=1)
    _stamp(fleet_dir, "fleet-supervise-done", 10.0, shards_done=3,
           retry_spend_s=0.5)
    _stamp(fleet_dir, "fleet-merge-done", 12.0, wall_s=2.0)
    return fleet_dir


def _write_shard_report(fleet_dir, sid, version=None, flow=None):
    rep = report_mod.assemble("cluster", started_at=0.0)
    if version is not None:
        rep["version"] = version
    if flow is not None:
        rep["flow"] = flow
    report_mod.write(sched_mod.shard_report_path(fleet_dir, sid), rep)


# -- rollup conservation + blame ------------------------------------


def test_rollup_conserves_the_fleet_wall(tmp_path):
    fleet_dir = _synthetic_fleet(tmp_path)
    ru = fleet_view.rollup(fleet_dir)
    assert ru is not None
    wall = ru["fleet_wall_s"]
    assert wall == pytest.approx(12.0)
    blame = sum(c["blame_s"] for c in ru["components"].values())
    assert blame == pytest.approx(wall, abs=1e-6)
    comps = ru["components"]
    assert comps["merge"]["blame_s"] == pytest.approx(2.0)
    # the only uncovered supervise time is the 4.0..4.5 backoff gap
    # (shards 0/2 were both done or running through it? no: shard 0
    # ran 0..6 so coverage is continuous 0..10 — scheduler blame 0)
    assert comps["scheduler"]["blame_s"] == pytest.approx(0.0)
    assert comps["scheduler"]["backoff_s"] <= 0.5
    # walls: shard0=6, shard1=9.5, shard2=6 -> median 6, coverage 10
    assert ru["shards"]["1"]["wall_s"] == pytest.approx(9.5)
    assert comps["straggler_wait"]["blame_s"] == pytest.approx(4.0)
    assert comps["straggler_wait"]["slowest"][0]["shard"] == 1
    assert ru["shards"]["1"]["attempts"] == 2
    assert ru["shards"]["1"]["preemptions"] == 1
    assert ru["bottleneck"]  # named, deterministic timeline


def test_rollup_blames_shard_stages_via_flow_critical_path(tmp_path):
    fleet_dir = _synthetic_fleet(tmp_path)
    flow = {"critical_path": {
        "bottleneck": "sketch",
        "stages": {"sketch": {"share": 0.75},
                   "pairs": {"share": 0.25}}}}
    _write_shard_report(fleet_dir, 1, flow=flow)
    ru = fleet_view.rollup(fleet_dir)
    sh = ru["shards"]["1"]
    assert sh["bottleneck"] == "sketch"
    assert sh["stages"]["sketch"]["blame_s"] == pytest.approx(
        0.75 * sh["blame_s"], abs=1e-5)
    # the fleet bottleneck narrows a winning shard to its stage
    if ru["bottleneck"].startswith("shard-1"):
        assert ru["bottleneck"] == "shard-1:sketch"
    lines = fleet_view.render_rollup(ru)
    body = "\n".join(lines)
    assert "fleet critical path" in body
    assert "bottleneck:" in body


def test_rollup_requires_an_event_log(tmp_path):
    assert fleet_view.rollup(str(tmp_path)) is None


# -- tolerance: torn tails, deleted shards, old reports --------------


def test_rollup_tolerates_torn_tail_and_deleted_shard(tmp_path):
    fleet_dir = _synthetic_fleet(tmp_path)
    _write_shard_report(fleet_dir, 0)
    # a SIGKILL mid-append leaves a torn event tail
    with open(plan_mod.events_path(fleet_dir), "a") as fh:
        fh.write('{"ev": "shard-done", "truncat')
    # a torn heartbeat tail on shard 0
    hb_path = sched_mod.shard_heartbeat_path(fleet_dir, 0)
    with open(hb_path, "a") as fh:
        fh.write('{"beat": 99, "truncat')
    # shard 2's dir deleted mid-aggregate (preempted node reclaimed)
    import shutil
    shutil.rmtree(os.path.join(fleet_dir, "shards", "shard_002"))
    ru = fleet_view.rollup(fleet_dir)
    assert ru is not None and ru["source"]["torn_events"] == 1
    assert 2 in ru["source"]["shards_missing"]
    assert ru["source"]["shards_reported"] == 1
    blame = sum(c["blame_s"] for c in ru["components"].values())
    assert blame == pytest.approx(ru["fleet_wall_s"], abs=1e-6)


def test_rollup_accepts_old_schema_shard_reports(tmp_path):
    fleet_dir = _synthetic_fleet(tmp_path)
    _write_shard_report(fleet_dir, 0, version=6)  # pre-flow-CP era
    _write_shard_report(fleet_dir, 1)             # current v9
    ru = fleet_view.rollup(fleet_dir)
    assert sorted(ru["source"]["schema_versions"]) == [
        6, report_mod.REPORT_VERSION]
    assert ru["shards"]["0"]["report_version"] == 6
    blame = sum(c["blame_s"] for c in ru["components"].values())
    assert blame == pytest.approx(ru["fleet_wall_s"], abs=1e-6)


def test_report_diff_mixed_v6_vs_v9_rollup(tmp_path, capsys):
    from galah_tpu.cli import main

    old = report_mod.assemble("cluster", started_at=0.0)
    old["version"] = 6
    old.pop("fleet_rollup", None)
    new = report_mod.assemble("cluster", started_at=0.0)
    new["fleet_rollup"] = fleet_view.rollup(
        _synthetic_fleet(tmp_path))
    pa = str(tmp_path / "old.json")
    pb = str(tmp_path / "new.json")
    report_mod.write(pa, old)
    report_mod.write(pb, new)
    assert main(["report", "--diff", pa, pb]) == 0
    out = capsys.readouterr().out
    assert "fleet rollup drift:" in out
    assert "fleet_wall_s: 0.00 -> 12.00" in out
    assert "share[straggler_wait]" in out


# -- fleet grid + top fleet mode -------------------------------------


def test_fleet_grid_states_and_event_tail(tmp_path):
    fleet_dir = _synthetic_fleet(tmp_path)
    grid = fleet_view.fleet_grid(fleet_dir)
    assert grid["shards"]["0"]["state"] == "done"
    assert grid["shards"]["1"]["attempts"] == 2
    assert grid["shards"]["1"]["chain"] == ["worker-exit"]
    assert grid["event_tail"][-1]["ev"] == "fleet-merge-done"
    page = fleet_view.render_fleet_grid(grid)
    assert "shard   1" in page and "worker-exit" in page
    assert fleet_view.fleet_grid(str(tmp_path / "nope")) is None


def test_top_subcommand_fleet_mode_and_json(tmp_path, capsys):
    from galah_tpu.cli import main

    fleet_dir = _synthetic_fleet(tmp_path)
    # a beat inside shard 1's dir feeds the grid's liveness columns
    hb = obs_heartbeat.Heartbeat(
        os.path.join(fleet_dir, "shards", "shard_001"), 60.0)
    hb.beat()
    assert main(["top", fleet_dir]) == 0
    out = capsys.readouterr().out
    assert "fleet" in out and "shard" in out
    assert main(["top", "--json", fleet_dir]) == 0
    grid = json.loads(capsys.readouterr().out)
    assert grid["shards"]["1"]["beat_age_s"] >= 0.0
    # single-run dir --json: the latest beat record
    single = tmp_path / "single"
    single.mkdir()
    hb2 = obs_heartbeat.Heartbeat(str(single), 60.0)
    hb2.beat()
    assert main(["top", "--json", str(single)]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["beat"] == 1
    assert main(["top", "--json", str(tmp_path / "empty")]) == 1


# -- fleet analyze CLI -----------------------------------------------


def test_fleet_analyze_renders_writes_and_validates(tmp_path, capsys):
    from galah_tpu.cli import main

    fleet_dir = _synthetic_fleet(tmp_path)
    assert main(["fleet", "analyze", fleet_dir]) == 0
    out = capsys.readouterr().out
    assert "fleet critical path" in out and "bottleneck:" in out
    rep_path = fleet_view.fleet_report_path(fleet_dir)
    assert os.path.exists(rep_path)
    with open(rep_path) as f:
        rep = json.load(f)
    assert report_mod.validate(rep) == []
    assert rep["fleet_rollup"]["fleet_wall_s"] == pytest.approx(12.0)
    # --json mode: machine-readable rollup on stdout
    assert main(["fleet", "analyze", "--json", "--no-report",
                 fleet_dir]) == 0
    ru = json.loads(capsys.readouterr().out)
    assert ru["bottleneck"]


def test_fleet_analyze_exit_1_on_rollup_impossible(tmp_path):
    from galah_tpu.cli import main

    empty = tmp_path / "not_a_fleet"
    empty.mkdir()
    assert main(["fleet", "analyze", str(empty)]) == 1


# -- heartbeat role/shard stamps -------------------------------------


def test_heartbeat_stamps_role_and_shard(tmp_path, monkeypatch):
    sdir = tmp_path / "shards" / "shard_007"
    sdir.mkdir(parents=True)
    monkeypatch.setenv("GALAH_TPU_FLEET_WORKER", str(tmp_path))
    hb = obs_heartbeat.Heartbeat(str(sdir), 60.0)
    hb.beat()
    rec = obs_heartbeat.read_latest_beat(hb.path)
    assert rec["role"] == "worker" and rec["shard"] == 7
    assert isinstance(rec.get("rss_mb"), (int, float))
    page = obs_heartbeat.render_latest(str(sdir))
    assert "role worker (shard 7)" in page
    # explicit role wins over inference
    monkeypatch.delenv("GALAH_TPU_FLEET_WORKER")
    hb2 = obs_heartbeat.Heartbeat(str(tmp_path), 60.0,
                                  role="scheduler")
    hb2.beat()
    assert obs_heartbeat.read_latest_beat(hb2.path)["role"] \
        == "scheduler"


def test_unstamped_beats_read_clean(tmp_path):
    # beats written before the role/shard stamps existed must load
    # and render without either key
    path = str(tmp_path / "heartbeat.jsonl")
    atomic.append_jsonl(path, {"beat": 1, "ts": 1.0, "pid": 1,
                               "occupancy": {}},
                        site="obs.heartbeat")
    rec = obs_heartbeat.read_latest_beat(path)
    assert rec["beat"] == 1
    assert "role" not in rec and "shard" not in rec
    page = obs_heartbeat.render_latest(str(tmp_path))
    assert "beat 1" in page and "role" not in page


# -- sharded perf-ledger keys ----------------------------------------


def test_ledger_shard_key_never_mixes_with_e2e(tmp_path):
    rep = report_mod.assemble("cluster", started_at=0.0)
    plain = ledger_mod.entry_from_report(rep, "cluster")
    sharded = ledger_mod.entry_from_report(rep, "cluster", shard=2)
    assert "shard" not in plain["key"]
    assert sharded["key"]["shard"] == 2
    assert ledger_mod.key_of(plain) != ledger_mod.key_of(sharded)
    # distinct shards are distinct histories too
    other = ledger_mod.entry_from_report(rep, "cluster", shard=3)
    assert ledger_mod.key_of(sharded) != ledger_mod.key_of(other)


def test_finalize_brands_ledger_entries_with_shard_context(
        tmp_path, monkeypatch):
    ledger = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("GALAH_OBS_LEDGER", str(ledger))
    sdir = tmp_path / "fleet" / "shards" / "shard_004"
    sdir.mkdir(parents=True)
    # worker stamp + shard path -> sharded key
    monkeypatch.setenv("GALAH_TPU_FLEET_WORKER",
                       str(tmp_path / "fleet"))
    obs.finalize("cluster", report_path=str(sdir / "run_report.json"))
    # no stamp -> plain key even under a shard-shaped path
    monkeypatch.delenv("GALAH_TPU_FLEET_WORKER")
    obs.finalize("cluster", report_path=str(sdir / "run_report.json"))
    entries, torn = ledger_mod.read(str(ledger))
    assert torn == 0 and len(entries) == 2
    assert entries[0]["key"].get("shard") == 4
    assert "shard" not in entries[1]["key"]


# -- OpenMetrics textfile exporter -----------------------------------


def _populate_metrics():
    obs_metrics.counter("cache.hits", help="cache hits").inc(3)
    obs_metrics.gauge("fleet.workers_live",
                      help="live workers").set(2)
    obs_metrics.histogram("ani.batch_seconds", unit="s",
                          help="batch walls").observe(0.5)
    obs_metrics.pipeline_occupancy(0.8, stage="sketch")


def test_openmetrics_page_parses_under_prometheus_parser(tmp_path):
    parser = pytest.importorskip("prometheus_client.parser")
    _populate_metrics()
    ru = fleet_view.rollup(_synthetic_fleet(tmp_path))
    page = openmetrics.render(obs_metrics.snapshot(), rollup=ru)
    fams = {f.name: f for f in
            parser.text_string_to_metric_families(page)}
    assert fams["galah_cache_hits"].type == "counter"
    assert fams["galah_fleet_workers_live"].type == "gauge"
    assert "galah_ani_batch_seconds" in fams
    occ = [s for s in
           fams["galah_workload_pipeline_occupancy"].samples]
    assert occ[0].labels == {"stage": "sketch"}
    blame = {s.labels["component"]: s.value for s in
             fams["galah_fleet_blame_seconds"].samples}
    assert blame["merge"] == pytest.approx(2.0)
    walls = [s for s in fams["galah_fleet_wall_seconds"].samples]
    assert walls[0].value == pytest.approx(12.0)


def test_openmetrics_export_is_atomic_and_env_gated(tmp_path,
                                                    monkeypatch):
    monkeypatch.delenv("GALAH_OBS_OPENMETRICS", raising=False)
    assert openmetrics.maybe_export() is None  # no env -> no-op
    out = tmp_path / "om" / "galah.prom"
    out.parent.mkdir()
    monkeypatch.setenv("GALAH_OBS_OPENMETRICS", str(out))
    _populate_metrics()
    assert openmetrics.maybe_export() == str(out)
    assert out.exists()
    assert not [p for p in os.listdir(out.parent)
                if p.endswith(".tmp")]
    assert "galah_cache_hits_total 3" in out.read_text()


def test_heartbeat_tick_drives_the_exporter(tmp_path, monkeypatch):
    out = tmp_path / "galah.prom"
    monkeypatch.setenv("GALAH_OBS_OPENMETRICS", str(out))
    _populate_metrics()
    hb = obs_heartbeat.Heartbeat(str(tmp_path), 60.0)
    hb.beat()
    assert out.exists()
    assert "galah_fleet_workers_live 2" in out.read_text()
