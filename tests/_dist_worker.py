"""Worker for the two-process jax.distributed smoke test.

Launched by tests/test_multiprocess.py: each process owns 4 virtual CPU
devices (8 global), ingests its strided host_shard of a deterministic
sketch matrix, assembles the global row-sharded array, and runs the
sharded pair count over the global mesh. Usage:

    python tests/_dist_worker.py <coordinator> <num_procs> <proc_id>
"""

import os
import sys


def main() -> int:
    coord, n_proc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    os.environ.setdefault("JAX_ENABLE_X64", "1")

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from galah_tpu.parallel import distributed
    from galah_tpu.parallel.mesh import sharded_pair_count

    distributed.initialize(coordinator_address=coord,
                           num_processes=n_proc, process_id=pid)
    assert distributed.process_count() == n_proc
    assert jax.device_count() == 4 * n_proc

    # Deterministic global sketch set with planted duplicate rows.
    global_n, width = 16, 64
    rng = np.random.default_rng(0)
    mat = rng.integers(0, 1 << 63, size=(global_n, width),
                       dtype=np.uint64)
    mat.sort(axis=1)
    mat[9] = mat[2]
    mat[13] = mat[5]

    # Each host "ingests" only its strided shard, as production would.
    mine = np.array(distributed.host_shard(list(range(global_n))))
    local_rows = mat[mine]

    mesh = distributed.global_mesh()
    garr = distributed.global_sketch_matrix(local_rows, global_n, mesh)

    # The assembled array must equal the contiguous global matrix:
    # every addressable shard is checked against its global rows.
    for shard in garr.addressable_shards:
        r0 = shard.index[0].start or 0
        np.testing.assert_array_equal(
            np.asarray(shard.data),
            mat[r0:r0 + shard.data.shape[0]])

    count = sharded_pair_count(mat, k=21, min_ani=0.99, mesh=mesh,
                               col_tile=8)
    print(f"COUNT {pid} {count}", flush=True)

    # Optional end-to-end mode: cluster a shared genome directory with
    # per-host ingestion (MinHashPreclusterer splits FASTA reading +
    # sketching across hosts and exchanges sketch rows); every process
    # must print the identical composition.
    if len(sys.argv) > 4:
        import glob
        import json

        from galah_tpu.backends import (
            MinHashPreclusterer,
            ProfileStore,
            SkaniEquivalentClusterer,
        )
        from galah_tpu.cluster import cluster

        from galah_tpu.backends import HLLPreclusterer

        paths = sorted(glob.glob(os.path.join(sys.argv[4], "*.fna")))
        store = ProfileStore(k=15)
        cl = SkaniEquivalentClusterer(
            threshold=0.95, min_aligned_fraction=0.2, store=store)
        clusters = cluster(paths, MinHashPreclusterer(min_ani=0.9), cl)
        got = sorted(sorted(c) for c in clusters)
        print(f"CLUSTERS {pid} {json.dumps(got)}", flush=True)

        # dashing-equivalent precluster path, same per-host ingestion
        clusters2 = cluster(paths, HLLPreclusterer(min_ani=0.9), cl)
        got2 = sorted(sorted(c) for c in clusters2)
        print(f"CLUSTERS_HLL {pid} {json.dumps(got2)}", flush=True)

        # the DEFAULT combo (skani+skani): per-host marker profiling +
        # host-sharded exact ANI with result exchange; skip_clusterer
        # reuses the exchanged ANIs so the whole pipeline is split
        from galah_tpu.backends import SkaniPreclusterer

        pre3 = SkaniPreclusterer(threshold=0.9, min_aligned_fraction=0.2,
                                 store=store)
        clusters3 = cluster(paths, pre3, cl)
        got3 = sorted(sorted(c) for c in clusters3)
        print(f"CLUSTERS_SKANI {pid} {json.dumps(got3)}", flush=True)

        # failure symmetry: one host fails its shard of a distributed
        # pass; EVERY process must raise (nobody strands in the
        # collective)
        def _compute(idxs):
            if pid == 1:
                raise RuntimeError("planted shard failure")
            return [1.0] * len(idxs)

        try:
            distributed.sharded_optional_floats(8, _compute)
            print(f"FAILTEST {pid} NORAISE", flush=True)
        except Exception:
            print(f"FAILTEST {pid} RAISED", flush=True)

        # quality ranking with the host-split stats pass: every host
        # must produce the identical order
        info = os.path.join(sys.argv[4], "info.csv")
        if os.path.exists(info):
            from galah_tpu.quality import (
                filter_and_order_genomes,
                read_genome_info_file,
            )

            table = read_genome_info_file(info)
            ordered = filter_and_order_genomes(
                paths, table, formula="Parks2020_reduced")
            print(f"ORDER {pid} "
                  f"{json.dumps([os.path.basename(p) for p in ordered])}",
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
