"""MinHash sketch engine: murmur3 correctness, numpy/JAX parity, golden ANI.

Golden oracle: set1 1mbp vs 500kb -> ANI 0.9808188 at k=21, 1000 hashes,
seed 0 (reference: src/finch.rs:85-107).
"""

import struct

import numpy as np
import pytest

from galah_tpu.io import read_genome
from galah_tpu.ops import minhash_np
from galah_tpu.ops.murmur3_np import murmur3_x64_128_h1


def _mm3_scalar(key: bytes, seed: int = 0):
    """Independent scalar murmur3 x64_128 for cross-checking the
    vectorized implementation (verified via the SMHasher constant below)."""
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    def fmix(x):
        x ^= x >> 33
        x = (x * 0xFF51AFD7ED558CCD) & M
        x ^= x >> 33
        x = (x * 0xC4CEB9FE1A85EC53) & M
        x ^= x >> 33
        return x

    c1, c2 = 0x87C37B91114253D5, 0x4CF5AD432745937F
    h1 = h2 = seed & M
    nblocks = len(key) // 16
    for i in range(nblocks):
        k1 = int.from_bytes(key[i * 16:i * 16 + 8], "little")
        k2 = int.from_bytes(key[i * 16 + 8:i * 16 + 16], "little")
        k1 = (k1 * c1) & M
        k1 = rotl(k1, 31)
        k1 = (k1 * c2) & M
        h1 ^= k1
        h1 = rotl(h1, 27)
        h1 = (h1 + h2) & M
        h1 = (h1 * 5 + 0x52DCE729) & M
        k2 = (k2 * c2) & M
        k2 = rotl(k2, 33)
        k2 = (k2 * c1) & M
        h2 ^= k2
        h2 = rotl(h2, 31)
        h2 = (h2 + h1) & M
        h2 = (h2 * 5 + 0x38495AB5) & M
    tail = key[nblocks * 16:]
    k1 = k2 = 0
    rem = len(key) & 15
    for b in range(rem - 1, 7, -1):
        k2 ^= tail[b] << (8 * (b - 8))
    if rem > 8:
        k2 = (k2 * c2) & M
        k2 = rotl(k2, 33)
        k2 = (k2 * c1) & M
        h2 ^= k2
    for b in range(min(rem, 8) - 1, -1, -1):
        k1 ^= tail[b] << (8 * b)
    if rem > 0:
        k1 = (k1 * c1) & M
        k1 = rotl(k1, 31)
        k1 = (k1 * c2) & M
        h1 ^= k1
    h1 ^= len(key)
    h2 ^= len(key)
    h1 = (h1 + h2) & M
    h2 = (h2 + h1) & M
    h1 = fmix(h1)
    h2 = fmix(h2)
    h1 = (h1 + h2) & M
    h2 = (h2 + h1) & M
    return h1, h2


def test_murmur3_smhasher_verification():
    """SMHasher VerificationTest for MurmurHash3_x64_128 == 0x6384BA69."""
    buf = b""
    for i in range(256):
        h1, h2 = _mm3_scalar(bytes(range(i)), seed=256 - i)
        buf += struct.pack("<QQ", h1, h2)
    f1, f2 = _mm3_scalar(buf, 0)
    verif = struct.unpack("<I", struct.pack("<QQ", f1, f2)[:4])[0]
    assert verif == 0x6384BA69


def test_murmur3_numpy_matches_scalar():
    rng = np.random.default_rng(0)
    for length in [1, 5, 8, 16, 21, 31, 32, 48]:
        keys = rng.integers(0, 256, size=(16, length), dtype=np.uint8)
        got = murmur3_x64_128_h1(keys)
        for row in range(16):
            exp, _ = _mm3_scalar(keys[row].tobytes())
            assert int(got[row]) == exp


def test_murmur3_jax_matches_numpy():
    from galah_tpu.ops import hashing

    rng = np.random.default_rng(1)
    for length in [5, 16, 21, 32]:
        keys = rng.integers(0, 256, size=(8, length), dtype=np.uint8)
        np_h = murmur3_x64_128_h1(keys)
        jx_h = np.asarray(hashing.murmur3_x64_128_h1(keys))
        np.testing.assert_array_equal(np_h, jx_h)


def test_golden_finch_ani(ref_data):
    g1 = read_genome(str(ref_data / "set1" / "1mbp.fna"))
    g2 = read_genome(str(ref_data / "set1" / "500kb.fna"))
    s1 = minhash_np.sketch_genome(g1)
    s2 = minhash_np.sketch_genome(g2)
    ani = minhash_np.mash_ani(s1, s2)
    assert np.float32(ani) == np.float32(0.9808188)


@pytest.mark.parametrize("seq_len", [50, 3000])
def test_device_sketch_matches_numpy(tmp_path, seq_len):
    from galah_tpu.ops.minhash import sketch_genome_device

    rng = np.random.default_rng(2)
    seq = "".join(rng.choice(list("ACGT"), size=seq_len))
    # two contigs + an N to exercise masking
    p = tmp_path / "g.fna"
    p.write_text(f">a\n{seq[: seq_len // 2]}N{seq[seq_len // 2:]}\n"
                 f">b\n{seq[:40]}\n")
    g = read_genome(str(p))
    s_np = minhash_np.sketch_genome(g, sketch_size=64)
    s_dev = sketch_genome_device(g, sketch_size=64, chunk=1024)
    np.testing.assert_array_equal(s_np.hashes, s_dev.hashes)


def test_device_sketch_golden_chunked(ref_data):
    """Chunked device sketching reproduces the golden on real data."""
    from galah_tpu.ops.minhash import sketch_genome_device

    g1 = read_genome(str(ref_data / "set1" / "1mbp.fna"))
    g2 = read_genome(str(ref_data / "set1" / "500kb.fna"))
    s1 = sketch_genome_device(g1)
    s2 = sketch_genome_device(g2)
    ani = minhash_np.mash_ani(s1, s2)
    assert np.float32(ani) == np.float32(0.9808188)


def test_batch_sketch_matches_single(tmp_path, ref_data):
    """sketch_genomes_device_batch is bit-identical to the per-genome
    chunked path across length buckets, contig breaks, and N masking."""
    from galah_tpu.ops.minhash import (
        sketch_genome_device,
        sketch_genomes_device_batch,
    )

    rng = np.random.default_rng(5)
    genomes = []
    for i, seq_len in enumerate([80, 3000, 70_000, 70_500]):
        seq = "".join(rng.choice(list("ACGT"), size=seq_len))
        p = tmp_path / f"g{i}.fna"
        p.write_text(f">a\n{seq[: seq_len // 2]}N{seq[seq_len // 2:]}\n"
                     f">b\n{seq[:50]}\n")
        genomes.append(read_genome(str(p)))
    genomes.append(read_genome(str(ref_data / "set1" / "500kb.fna")))

    batch = sketch_genomes_device_batch(genomes, sketch_size=64)
    for g, s in zip(genomes, batch):
        single = sketch_genome_device(g, sketch_size=64)
        np.testing.assert_array_equal(single.hashes, s.hashes)


def test_batch_sketch_tiny_budget_groups(tmp_path):
    """Groups split by the position budget still cover every genome."""
    from galah_tpu.ops.minhash import sketch_genomes_device_batch

    rng = np.random.default_rng(6)
    genomes = []
    for i in range(5):
        seq = "".join(rng.choice(list("ACGT"), size=500 + 17 * i))
        p = tmp_path / f"t{i}.fna"
        p.write_text(f">c\n{seq}\n")
        genomes.append(read_genome(str(p)))
    a = sketch_genomes_device_batch(genomes, sketch_size=32,
                                    budget=1 << 16)
    b = sketch_genomes_device_batch(genomes, sketch_size=32)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x.hashes, y.hashes)


@pytest.mark.slow
def test_preclusterer_batched_branch_matches(tmp_path, monkeypatch):
    """The backend's TPU-policy batched sketch branch produces the same
    pair cache as the per-genome CPU branch. Slow tier: compile-bound
    XLA-CPU parity (two full sketch-compile pipelines over 40 kb
    genomes); the branch's integers are also pinned by the golden
    cluster tests whenever the TPU policy is active."""
    from galah_tpu.backends.minhash_backend import MinHashPreclusterer
    from galah_tpu.io.diskcache import CacheDir

    rng = np.random.default_rng(31)
    base = rng.choice(list("ACGT"), size=40_000)
    paths = []
    for i in range(4):
        seq = base.copy()
        if i >= 2:  # second family
            sites = rng.random(seq.shape[0]) < 0.03
            repl = rng.choice(list("ACGT"), size=int(sites.sum()))
            seq[sites] = repl
        p = tmp_path / f"m{i}.fna"
        p.write_text(">c\n" + "".join(seq) + "\n")
        paths.append(str(p))

    plain = MinHashPreclusterer(
        0.95, cache=CacheDir(str(tmp_path / "c1"))).distances(paths)
    monkeypatch.setenv("GALAH_PACKED_TRANSFER", "1")
    batched = MinHashPreclusterer(
        0.95, cache=CacheDir(str(tmp_path / "c2"))).distances(paths)
    assert dict(plain.items()) == dict(batched.items())
