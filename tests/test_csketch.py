"""Native C MinHash sketcher: bit-parity with the numpy/JAX pipelines
(reference analog: finch's compiled sketching, src/finch.rs:33-47)."""

import numpy as np
import pytest

from galah_tpu.io import read_genome
from galah_tpu.ops import minhash_np

csk = pytest.importorskip("galah_tpu.ops._csketch")


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return read_genome(str(p))


@pytest.mark.parametrize("seq_len", [25, 3000, 70_000])
def test_c_matches_numpy(tmp_path, seq_len):
    rng = np.random.default_rng(7)
    seq = "".join(rng.choice(list("ACGT"), size=seq_len))
    g = _write(tmp_path, "g.fna",
               f">a\n{seq[: seq_len // 2]}N{seq[seq_len // 2:]}\n"
               f">b\n{seq[:40]}\n")
    want = minhash_np.sketch_genome(g, sketch_size=64)
    got = csk.sketch_bottomk(g.codes, g.contig_offsets, k=21,
                             sketch_size=64, seed=0, algo="murmur3")
    np.testing.assert_array_equal(want.hashes, got)


def test_c_golden_finch_ani(ref_data):
    g1 = read_genome(str(ref_data / "set1" / "1mbp.fna"))
    g2 = read_genome(str(ref_data / "set1" / "500kb.fna"))
    h1 = csk.sketch_bottomk(g1.codes, g1.contig_offsets, 21, 1000, 0,
                            "murmur3")
    h2 = csk.sketch_bottomk(g2.codes, g2.contig_offsets, 21, 1000, 0,
                            "murmur3")
    a = minhash_np.MinHashSketch(h1, 1000, 21)
    b = minhash_np.MinHashSketch(h2, 1000, 21)
    assert np.float32(minhash_np.mash_ani(a, b)) == np.float32(0.9808188)


def test_c_tpufast_matches_jax(tmp_path):
    from galah_tpu.ops.minhash import sketch_genome_device

    rng = np.random.default_rng(9)
    seq = "".join(rng.choice(list("ACGT"), size=20_000))
    g = _write(tmp_path, "t.fna", f">a\n{seq}\nN\n>b\n{seq[:90]}\n")
    # chunk=2048 pins the JAX pipeline (non-default chunk)
    want = sketch_genome_device(g, sketch_size=128, algo="tpufast",
                                chunk=2048)
    got = csk.sketch_bottomk(g.codes, g.contig_offsets, k=21,
                             sketch_size=128, seed=0, algo="tpufast")
    np.testing.assert_array_equal(want.hashes, got)


def test_sketch_genome_device_uses_c_on_cpu(tmp_path):
    """Default-path sketch_genome_device output equals the pinned JAX
    chunk pipeline (exercises the C fast-path switch)."""
    from galah_tpu.ops.minhash import sketch_genome_device

    rng = np.random.default_rng(10)
    seq = "".join(rng.choice(list("ACGT"), size=30_000))
    g = _write(tmp_path, "c.fna", f">a\n{seq}\n")
    default = sketch_genome_device(g, sketch_size=100)
    pinned_jax = sketch_genome_device(g, sketch_size=100, chunk=4096)
    np.testing.assert_array_equal(default.hashes, pinned_jax.hashes)


def test_c_short_and_empty(tmp_path):
    g = _write(tmp_path, "s.fna", ">a\nACGTACGT\n")
    out = csk.sketch_bottomk(g.codes, g.contig_offsets, 21, 64, 0,
                             "murmur3")
    assert out.shape == (0,)


def test_c_positional_hashes_matches_jax(tmp_path):
    """C positional hashes equal the JAX chunk pipeline entry-for-entry
    (SENTINEL masking at N bases and contig boundaries included)."""
    from galah_tpu.ops import fragment_ani

    rng = np.random.default_rng(12)
    seq = "".join(rng.choice(list("ACGT"), size=12_000))
    g = _write(tmp_path, "p.fna",
               f">a\n{seq[:5000]}N{seq[5000:8000]}\n>b\n{seq[8000:]}\n")
    want = fragment_ani.positional_hashes(g, k=15, chunk=2048)  # JAX
    got = csk.positional_hashes(g.codes, g.contig_offsets, k=15)
    np.testing.assert_array_equal(want, got)
    # and the default path (C on CPU) agrees too
    np.testing.assert_array_equal(
        fragment_ani.positional_hashes(g, k=15), got)


def test_c_64bit_seed_parity(tmp_path):
    """Seeds above 2^32 hash identically to the JAX pipeline (the C ABI
    carries the full 64-bit seed)."""
    from galah_tpu.ops.minhash import sketch_genome_device

    rng = np.random.default_rng(13)
    seq = "".join(rng.choice(list("ACGT"), size=9000))
    g = _write(tmp_path, "z.fna", f">a\n{seq}\n")
    big = (1 << 40) + 12345
    for algo in ("murmur3", "tpufast"):
        want = sketch_genome_device(g, sketch_size=64, seed=big,
                                    algo=algo, chunk=2048)  # JAX path
        got = csk.sketch_bottomk(g.codes, g.contig_offsets, k=21,
                                 sketch_size=64, seed=big, algo=algo)
        np.testing.assert_array_equal(want.hashes, got)


def test_c_hll_registers_match_jax(tmp_path):
    """C HLL registers equal the JAX chunk pipeline bit-for-bit (both
    algos, N masking, contig break)."""
    from galah_tpu.ops import hll

    rng = np.random.default_rng(14)
    seq = "".join(rng.choice(list("ACGT"), size=25_000))
    g = _write(tmp_path, "h.fna",
               f">a\n{seq[:9000]}N{seq[9000:]}\n>b\n{seq[:70]}\n")
    for algo in ("murmur3", "tpufast"):
        want = hll.hll_sketch_genome(g, p=10, algo=algo, chunk=2048)
        got = csk.hll_registers(g.codes, g.contig_offsets, k=21, p=10,
                                seed=0, algo=algo)
        np.testing.assert_array_equal(np.asarray(want), got)
        # and the default path selects the C twin with identical output
        np.testing.assert_array_equal(
            np.asarray(hll.hll_sketch_genome(g, p=10, algo=algo)), got)


def test_positional_hashes_masked_parity():
    """The single-pass masked walk (flat + compacted valid) must equal
    positional_hashes + np.where + the != SENTINEL filter for every
    algo, subsample, contig structure, and ambiguity pattern."""
    from galah_tpu.ops import _csketch
    from galah_tpu.ops.constants import SENTINEL

    rng = np.random.default_rng(31)
    for trial in range(12):
        n = int(rng.integers(1, 4000))
        codes = rng.integers(0, 4, size=n).astype(np.uint8)
        # ambiguity islands
        for _ in range(int(rng.integers(0, 4))):
            s = int(rng.integers(0, n))
            codes[s:s + int(rng.integers(1, 9))] = 255
        n_contigs = int(rng.integers(1, 4))
        cuts = np.sort(rng.choice(np.arange(1, max(2, n)),
                                  size=n_contigs - 1, replace=False))
        offs = np.concatenate([[0], cuts, [n]]).astype(np.int64)
        k = int(rng.integers(1, 33))
        algo = ("murmur3", "tpufast")[trial % 2]
        c = (1, 4, 16, 125)[trial % 4]
        cut = 0 if c == 1 else (1 << 64) // c

        want_flat = _csketch.positional_hashes(codes, offs, k=k,
                                               algo=algo)
        if c > 1:
            want_flat = np.where(
                want_flat < np.uint64(cut), want_flat,
                np.uint64(SENTINEL))
        want_valid = want_flat[want_flat != np.uint64(SENTINEL)]

        flat, valid = _csketch.positional_hashes_masked(
            codes, offs, k=k, cut=cut, algo=algo)
        np.testing.assert_array_equal(flat, want_flat)
        np.testing.assert_array_equal(valid, want_valid)


def test_profile_via_c_matches_generic(tmp_path):
    """The C single-pass profile equals the generic build exactly."""
    import jax

    from galah_tpu.io.fasta import Genome, GenomeStats
    from galah_tpu.ops.fragment_ani import (_profile_from_flat,
                                            _profile_via_c,
                                            positional_hashes)

    assert jax.default_backend() == "cpu"
    rng = np.random.default_rng(32)
    codes = rng.integers(0, 4, size=30_000).astype(np.uint8)
    codes[500:520] = 255
    g = Genome(path="g.fna", codes=codes,
               contig_offsets=np.array([0, 11_000, 30_000],
                                       dtype=np.int64),
               stats=GenomeStats(2, 20, 19_000))
    for c in (1, 16):
        got = _profile_via_c(g, 15, 3000, c)
        assert got is not None
        want = _profile_from_flat(
            g.path, positional_hashes(g, 15), 15, 3000, c)
        np.testing.assert_array_equal(got.flat_hashes, want.flat_hashes)
        np.testing.assert_array_equal(got.ref_set, want.ref_set)
        np.testing.assert_array_equal(got.markers, want.markers)
        assert (got.k, got.fraglen, got.subsample_c) == (
            want.k, want.fraglen, want.subsample_c)
