"""Tiled pairwise kernel: parity with the numpy merge, sharded execution."""

import numpy as np
import pytest

from galah_tpu.ops import minhash_np
from galah_tpu.ops.minhash import sketch_matrix
from galah_tpu.ops.minhash_np import MinHashSketch


def _random_sketches(rng, n, size, pool):
    sketches = []
    for _ in range(n):
        m = rng.integers(size // 2, size + 1)
        h = rng.choice(pool, size=m, replace=False).astype(np.uint64)
        sketches.append(MinHashSketch(
            hashes=np.sort(h), sketch_size=size, kmer=21))
    return sketches


def test_pair_stats_matches_numpy_merge():
    from galah_tpu.ops.pairwise import tile_stats

    rng = np.random.default_rng(0)
    pool = rng.integers(0, 1 << 62, size=400, dtype=np.uint64)
    pool = np.unique(pool)
    sketches = _random_sketches(rng, 12, 32, pool)
    mat = sketch_matrix(sketches, sketch_size=32)

    common, total = tile_stats(mat, mat, 32, 21)
    common, total = np.asarray(common), np.asarray(total)
    for i in range(12):
        for j in range(12):
            jac = minhash_np.mash_jaccard(sketches[i], sketches[j])
            t = int(total[i, j])
            assert t > 0
            assert common[i, j] / t == pytest.approx(jac)


def test_tile_ani_matches_numpy():
    from galah_tpu.ops.pairwise import tile_ani

    rng = np.random.default_rng(1)
    pool = np.unique(rng.integers(0, 1 << 62, size=600, dtype=np.uint64))
    sketches = _random_sketches(rng, 8, 64, pool)
    mat = sketch_matrix(sketches, sketch_size=64)
    ani = np.asarray(tile_ani(mat, mat, 64, 21))
    for i in range(8):
        for j in range(8):
            expect = minhash_np.mash_ani(sketches[i], sketches[j])
            assert ani[i, j] == pytest.approx(expect, abs=2e-5)


def test_all_pairs_sharded_8dev():
    import jax
    from galah_tpu.ops.pairwise import all_pairs_ani, tile_ani

    assert len(jax.devices()) == 8, "conftest must fake 8 CPU devices"
    rng = np.random.default_rng(2)
    pool = np.unique(rng.integers(0, 1 << 62, size=2000, dtype=np.uint64))
    sketches = _random_sketches(rng, 37, 64, pool)
    mat = sketch_matrix(sketches, sketch_size=64)

    full = all_pairs_ani(mat, k=21, col_tile=16)
    single = np.asarray(tile_ani(mat, mat, 64, 21))
    np.testing.assert_allclose(full, single, atol=1e-6)


def test_threshold_pairs_sparse():
    from galah_tpu.ops.pairwise import threshold_pairs

    rng = np.random.default_rng(3)
    pool = np.unique(rng.integers(0, 1 << 62, size=800, dtype=np.uint64))
    sketches = _random_sketches(rng, 21, 64, pool)
    mat = sketch_matrix(sketches, sketch_size=64)

    dense = np.zeros((21, 21))
    for i in range(21):
        for j in range(i + 1, 21):
            dense[i, j] = minhash_np.mash_ani(sketches[i], sketches[j])
    thr = float(np.quantile(dense[np.triu_indices(21, 1)], 0.8))

    sparse = threshold_pairs(mat, k=21, min_ani=thr,
                             row_tile=8, col_tile=8)
    expect = {(i, j): dense[i, j]
              for i in range(21) for j in range(i + 1, 21)
              if dense[i, j] >= thr}
    assert set(sparse) == set(expect)
    for key, v in expect.items():
        assert sparse[key] == pytest.approx(float(v), rel=1e-12)
