"""Test configuration: force an 8-device CPU mesh before JAX import.

Mirrors SURVEY.md §4's third tier: multi-device semantics are tested on CPU
via --xla_force_host_platform_device_count so no TPU (and no multi-chip
hardware) is needed to exercise the sharded pairwise path.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# The axon sitecustomize pins JAX_PLATFORMS to the TPU backend at
# interpreter startup; the config update below (before any jax use) wins.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

REFERENCE_DATA = pathlib.Path("/root/reference/tests/data")


@pytest.fixture(scope="session")
def ref_data() -> pathlib.Path:
    if not REFERENCE_DATA.is_dir():
        pytest.skip("reference fixture data not available")
    return REFERENCE_DATA
