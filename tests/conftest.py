"""Test configuration: force an 8-device CPU mesh before JAX import.

Mirrors SURVEY.md §4's third tier: multi-device semantics are tested on CPU
via --xla_force_host_platform_device_count so no TPU (and no multi-chip
hardware) is needed to exercise the sharded pairwise path.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# The axon sitecustomize pins JAX_PLATFORMS to the TPU backend at
# interpreter startup; the config update below (before any jax use) wins.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

REFERENCE_DATA = pathlib.Path("/root/reference/tests/data")

# Tier-1 runs under the GalahSan runtime concurrency sanitizer
# (docs/sanitizer.md): the threaded modules' declared locks are
# wrapped so the observed acquisition graph and GUARDED_BY mutations
# are validated under the real workload. GALAH_SAN=0 opts a run out
# (e.g. when bisecting a failure the instrumentation might mask).
# galah-lint: ignore[GL402] tier-1 opts in; the registry default (unset) is for production runs
os.environ.setdefault("GALAH_SAN", "1")

from galah_tpu.analysis import sanitizer as _galah_san  # noqa: E402

_galah_san.maybe_install()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute campaign/scale tests — skipped by default "
        "so the inner dev loop stays under ~5 min; run them with "
        "GALAH_RUN_SLOW=1 (or GALAH_RUN_CAMPAIGN=1, or -m slow)")
    config.addinivalue_line(
        "markers",
        "hardware: tests that require a real TPU — always skipped on "
        "CPU; `galah-tpu lint` (GL601) audits that every "
        "hardware-only test carries this or the slow marker")
    config.addinivalue_line(
        "markers",
        "fault_injection: seeded fault-injection tests of the "
        "resilience layer (retry/demote/quarantine) — fast, CPU-only, "
        "part of the default tier-1 run; select just them with "
        "-m fault_injection")
    config.addinivalue_line(
        "markers",
        "chaos: bounded kill-anywhere chaos smoke (subprocess runs "
        "interrupted by SIGTERM / GALAH_FI kill / fs faults, then "
        "resumed and byte-compared) — slow tier; run with -m chaos "
        "or GALAH_RUN_SLOW=1; the full 25-iteration acceptance pass "
        "is scripts/chaos_run.py")


def pytest_collection_modifyitems(config, items):
    """Default-skip @pytest.mark.slow unless explicitly requested.

    The goldens these tests pin still run in CI tiers and before any
    release claim: GALAH_RUN_SLOW=1 runs everything, and an explicit
    -m expression takes full control."""
    if (os.environ.get("GALAH_RUN_SLOW") == "1"
            or os.environ.get("GALAH_RUN_CAMPAIGN") == "1"
            or config.getoption("-m")):
        return
    skip = pytest.mark.skip(
        reason="slow tier; set GALAH_RUN_SLOW=1 to run")
    for item in items:
        if "slow" in item.keywords or "hardware" in item.keywords:
            item.add_marker(skip)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """One GalahSan line per session: the observed-graph totals and
    the must-be-zero violation counts. tests/test_sanitizer.py's gate
    test is what FAILS the run on violations; this line is where a
    human sees the numbers."""
    if not _galah_san.GLOBAL.installed:
        return
    s = _galah_san.GLOBAL.summary()
    terminalreporter.write_line(
        f"galah-san: {s['acquisitions']} acquisitions / "
        f"{s['locks']} locks, edges {s['edges_observed']} observed / "
        f"{s['edges_declared']} declared "
        f"({s['unexercised']} unexercised); violations: "
        f"{s['undeclared_acquisitions']} undeclared, "
        f"{s['undeclared_edges']} unordered, "
        f"{s['inversions']} inversions, {s['races']} races")


@pytest.fixture(scope="session")
def ref_data() -> pathlib.Path:
    if not REFERENCE_DATA.is_dir():
        pytest.skip("reference fixture data not available")
    return REFERENCE_DATA
