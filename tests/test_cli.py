"""CLI integration tests with golden outputs.

Mirrors the reference's assert_cli suite (reference:
tests/test_cmdline.rs:8-338) through `galah_tpu.cli.main` in-process —
the fixture genomes and expected cluster compositions are the same; the
line ORDER follows this framework's deterministic precluster-size-then-
rep ordering (the reference's order is thread-timing dependent).
"""

import os

import pytest

from galah_tpu.cli import main

DATA = "/root/reference/tests/data"

# The golden fixture genomes live in the reference checkout, which not
# every container bakes in. Where the data exists these tests must
# pass (strict=False only because an xpass is then the healthy state);
# where it doesn't they xfail instead of reporting 12 false failures.
needs_reference_data = pytest.mark.xfail(
    condition=not os.path.isdir(DATA),
    reason=f"reference fixture genomes not present ({DATA})",
    strict=False)


def _run(args):
    return main(args)


@needs_reference_data
def test_completeness_4contamination_quality_score(tmp_path):
    out = tmp_path / "clusters.tsv"
    rc = _run([
        "cluster", "--quality-formula", "completeness-4contamination",
        "--genome-fasta-files",
        f"{DATA}/abisko4/73.20120800_S1D.21.fna",
        f"{DATA}/abisko4/73.20110800_S2M.16.fna",
        "--precluster-method", "finch",
        "--output-cluster-definition", str(out),
        "--checkm-tab-table", f"{DATA}/abisko4/abisko4.csv",
    ])
    assert rc == 0
    assert out.read_text() == (
        f"{DATA}/abisko4/73.20120800_S1D.21.fna\t"
        f"{DATA}/abisko4/73.20120800_S1D.21.fna\n"
        f"{DATA}/abisko4/73.20120800_S1D.21.fna\t"
        f"{DATA}/abisko4/73.20110800_S2M.16.fna\n")


@needs_reference_data
def test_parks2020_reduced_quality_score(tmp_path):
    out = tmp_path / "clusters.tsv"
    rc = _run([
        "cluster", "--quality-formula", "Parks2020_reduced",
        "--genome-fasta-files",
        f"{DATA}/abisko4/73.20120800_S1D.21.fna",
        f"{DATA}/abisko4/73.20110800_S2M.16.fna",
        "--precluster-method", "finch",
        "--output-cluster-definition", str(out),
        "--checkm-tab-table", f"{DATA}/abisko4/abisko4.csv",
    ])
    assert rc == 0
    assert out.read_text() == (
        f"{DATA}/abisko4/73.20110800_S2M.16.fna\t"
        f"{DATA}/abisko4/73.20110800_S2M.16.fna\n"
        f"{DATA}/abisko4/73.20110800_S2M.16.fna\t"
        f"{DATA}/abisko4/73.20120800_S1D.21.fna\n")


@needs_reference_data
def test_output_symlink_directory(tmp_path):
    outdir = tmp_path / "reps"
    rc = _run([
        "cluster", "--quality-formula", "Parks2020_reduced",
        "--genome-fasta-files",
        f"{DATA}/set1/500kb.fna", f"{DATA}/set1/1mbp.fna",
        "--precluster-method", "finch",
        "--output-representative-fasta-directory", str(outdir),
    ])
    assert rc == 0
    link = outdir / "500kb.fna"
    assert link.is_symlink()
    assert not (outdir / "1mbp.fna").exists()


@needs_reference_data
def test_output_symlink_directory_preexisting_empty(tmp_path):
    outdir = tmp_path / "reps"
    outdir.mkdir()
    rc = _run([
        "cluster",
        "--genome-fasta-files",
        f"{DATA}/set1/500kb.fna", f"{DATA}/set1/1mbp.fna",
        "--precluster-method", "finch",
        "--output-representative-fasta-directory", str(outdir),
    ])
    assert rc == 0
    assert (outdir / "500kb.fna").is_symlink()


@needs_reference_data
def test_output_directory_names_clash_copy(tmp_path):
    outdir = tmp_path / "reps"
    rc = _run([
        "cluster",
        "--genome-fasta-files",
        f"{DATA}/set1_name_clash/500kb.fna",
        f"{DATA}/set1/500kb.fna",
        f"{DATA}/set1/1mbp.fna",
        "--precluster-method", "finch",
        "--output-representative-fasta-directory-copy", str(outdir),
    ])
    assert rc == 0
    assert (outdir / "500kb.fna").exists()
    assert not (outdir / "500kb.fna").is_symlink()
    assert (outdir / "500kb.fna.1.fna").exists()
    assert not (outdir / "1mbp.fna").exists()


@needs_reference_data
def test_output_representative_list(tmp_path):
    out = tmp_path / "reps.txt"
    rc = _run([
        "cluster",
        "--genome-fasta-files",
        f"{DATA}/set1_name_clash/500kb.fna",
        f"{DATA}/set1/500kb.fna",
        f"{DATA}/set1/1mbp.fna",
        "--precluster-method", "finch",
        "--output-representative-list", str(out),
    ])
    assert rc == 0
    # biggest precluster first: {set1/500kb, 1mbp} then the clash genome
    assert out.read_text() == (
        f"{DATA}/set1/500kb.fna\n{DATA}/set1_name_clash/500kb.fna\n")


@needs_reference_data
def test_min_aligned_fraction(tmp_path):
    """Reference: tests/test_cmdline.rs:216-255 — 0.2 clusters the
    half-aligned pair, 0.6 splits it."""
    out = tmp_path / "reps.txt"
    rc = _run([
        "cluster",
        "--genome-fasta-files",
        f"{DATA}/set2/1mbp.fna", f"{DATA}/set2/1mbp.half_aligned.fna",
        "--min-aligned-fraction", "0.2",
        "--precluster-method", "finch",
        "--output-representative-list", str(out),
    ])
    assert rc == 0
    assert out.read_text() == f"{DATA}/set2/1mbp.fna\n"

    out2 = tmp_path / "reps2.txt"
    rc = _run([
        "cluster",
        "--genome-fasta-files",
        f"{DATA}/set2/1mbp.fna", f"{DATA}/set2/1mbp.half_aligned.fna",
        "--min-aligned-fraction", "0.6",
        "--precluster-method", "finch",
        "--output-representative-list", str(out2),
    ])
    assert rc == 0
    assert out2.read_text() == (
        f"{DATA}/set2/1mbp.fna\n{DATA}/set2/1mbp.half_aligned.fna\n")


@needs_reference_data
def test_github7_aligned_fraction_semantics(tmp_path):
    """Reference regression for galah issue #7
    (tests/test_cmdline.rs:316-338): the antonio MAG pair clusters at
    min-aligned-fraction 60."""
    out = tmp_path / "reps.txt"
    rc = _run([
        "cluster",
        "--genome-fasta-files",
        f"{DATA}/antonio_mags/BE_RX_R2_MAG52.fna",
        f"{DATA}/antonio_mags/BE_RX_R3_MAG189.fna",
        "--precluster-method", "finch",
        "--precluster-ani", "90", "--ani", "95",
        "--min-aligned-fraction", "60",
        "--output-representative-list", str(out),
    ])
    assert rc == 0
    assert out.read_text() == f"{DATA}/antonio_mags/BE_RX_R2_MAG52.fna\n"


@pytest.mark.slow
def test_skani_skani_precluster_threshold_override(tmp_path):
    """Reference: tests/test_cmdline.rs test_skani_skani_clusterer —
    with skani+skani, --precluster-ani 99 is overridden by --ani 95 and
    all four MAGs land in one cluster."""
    out = tmp_path / "clusters.tsv"
    rc = _run([
        "cluster",
        "--genome-fasta-files",
        f"{DATA}/abisko4/73.20120800_S1X.13.fna",
        f"{DATA}/abisko4/73.20120600_S2D.19.fna",
        f"{DATA}/abisko4/73.20120700_S3X.12.fna",
        f"{DATA}/abisko4/73.20110800_S2D.13.fna",
        "--precluster-method", "skani", "--cluster-method", "skani",
        "--precluster-ani", "99", "--ani", "95",
        "--output-cluster-definition", str(out),
        "--checkm-tab-table", f"{DATA}/abisko4/abisko4.csv",
    ])
    assert rc == 0
    lines = out.read_text().splitlines()
    assert len(lines) == 4
    rep = f"{DATA}/abisko4/73.20120800_S1X.13.fna"
    assert all(line.startswith(rep + "\t") for line in lines)


@needs_reference_data
def test_cluster_validate_roundtrip(tmp_path):
    clusters = tmp_path / "clusters.tsv"
    rc = _run([
        "cluster",
        "--genome-fasta-files",
        f"{DATA}/set1/500kb.fna", f"{DATA}/set1/1mbp.fna",
        "--precluster-method", "finch", "--cluster-method", "fastani",
        "--output-cluster-definition", str(clusters),
    ])
    assert rc == 0
    rc = _run([
        "cluster-validate", "--cluster-file", str(clusters),
        "--ani", "95", "--min-aligned-fraction", "20",
    ])
    assert rc == 0


def test_cluster_validate_unit_semantics(monkeypatch, tmp_path):
    """--ani 99 and --ani 0.99 both mean fraction 0.99 (PARITY.md).

    The reference parses the flag to a fraction and then multiplies by
    100 because its fastANI wrapper works in percent units (reference:
    src/cluster_validation.rs:13) — the two spellings coincide there
    too, so the CLI contract is identical; this framework simply stays
    in fractions end to end. This test pins that recorded decision.
    """
    import galah_tpu.validate as validate_mod

    clusters = tmp_path / "clusters.tsv"
    g = f"{DATA}/set1/500kb.fna"
    clusters.write_text(f"{g}\t{g}\n")

    seen = []

    def spy(cluster_file, clusterer):
        seen.append(clusterer.ani_threshold)
        return 0

    monkeypatch.setattr(validate_mod, "validate_clusters", spy)
    for spelling in ("99", "0.99"):
        rc = _run([
            "cluster-validate", "--cluster-file", str(clusters),
            "--ani", spelling, "--min-aligned-fraction", "20",
        ])
        assert rc == 0
    assert seen == [0.99, 0.99]


def test_no_genome_input_errors():
    rc = _run(["cluster", "--output-representative-list", "/dev/null"])
    assert rc == 1


def test_missing_quality_entry_clean_error(tmp_path):
    info = tmp_path / "info.csv"
    info.write_text("genome,completeness,contamination\nother,90,1\n")
    rc = _run([
        "cluster", "-f", f"{DATA}/set1/500kb.fna",
        "--genome-info", str(info),
        "--quality-formula", "completeness-4contamination",
    ])
    assert rc == 1


def _write_fraglen_pair(tmp_path):
    """Synthetic pair whose clustering flips with --fragment-length.

    Port of the reference's disabled fraglen test
    (reference: tests/test_cmdline.rs:340-382 — commented out there, so
    its exact fixture outcomes are not a pinned contract): homology
    interleaved at sub-fragment scale (3000 bp homologous + 1500 bp
    random per 4500 bp period). At --fragment-length 3000 every window
    overlaps homology (aligned fraction 1.0 -> merges at 95% ANI); at
    1000 the random stretches resolve (aligned fraction ~0.78, gated
    out by --min-aligned-fraction 80 -> two clusters).
    """
    import numpy as np

    rng = np.random.default_rng(42)
    L = 60_000
    base = rng.integers(0, 4, size=L)
    query = base.copy()
    period, rnd_len = 4500, 1500
    for start in range(0, L, period):
        s = start + period - rnd_len
        e = min(start + period, L)
        if s < L:
            query[s:e] = rng.integers(0, 4, size=e - s)
    alphabet = np.frombuffer(b"ACGT", dtype=np.uint8)
    paths = []
    for name, seq in (("seq_a.fna", base), ("seq_b.fna", query)):
        p = tmp_path / name
        with open(p, "wb") as fh:
            fh.write(b">" + name.encode() + b"\n")
            fh.write(alphabet[seq].tobytes() + b"\n")
        paths.append(str(p))
    return paths


def test_fraglen_flag_flips_clustering(tmp_path):
    a, b = _write_fraglen_pair(tmp_path)
    common = [
        "cluster", "--genome-fasta-files", a, b,
        "--precluster-method", "finch", "--cluster-method", "fastani",
        "--ani", "95", "--min-aligned-fraction", "80",
    ]

    reps_default = tmp_path / "reps_default.txt"
    rc = _run(common + ["--output-representative-list",
                        str(reps_default)])
    assert rc == 0
    assert reps_default.read_text() == f"{a}\n"  # merged: one rep

    reps_1000 = tmp_path / "reps_1000.txt"
    rc = _run(common + ["--fragment-length", "1000",
                        "--output-representative-list", str(reps_1000)])
    assert rc == 0
    assert reps_1000.read_text() == f"{a}\n{b}\n"  # gated: two reps


@needs_reference_data
def test_dist_subcommand_golden_pair(tmp_path):
    """`dist` (the reference ships this subcommand disabled, reference:
    src/main.rs:88-114): all-pairs MinHash ANI TSV, pinning the golden
    set1 sketch ANI 0.9808188 (reference: src/finch.rs:96)."""
    out = tmp_path / "dist.tsv"
    rc = _run([
        "dist", "--genome-fasta-files",
        f"{DATA}/set1/1mbp.fna", f"{DATA}/set1/500kb.fna",
        "--output", str(out),
    ])
    assert rc == 0
    lines = out.read_text().splitlines()
    assert len(lines) == 1
    a, b, ani = lines[0].split("\t")
    assert a.endswith("1mbp.fna") and b.endswith("500kb.fna")
    assert abs(float(ani) - 0.9808188) < 5e-7


@needs_reference_data
def test_dist_min_ani_filters(tmp_path):
    out = tmp_path / "dist.tsv"
    rc = _run([
        "dist", "--genome-fasta-files",
        f"{DATA}/set1/1mbp.fna", f"{DATA}/set1/500kb.fna",
        "--min-ani", "99", "--output", str(out),
    ])
    assert rc == 0
    assert out.read_text() == ""  # 0.98 < 0.99: filtered out


def test_validate_output_paths_mirrors_setup(tmp_path):
    """Non-writer validation must agree with setup_outputs case for
    case — disagreement would stall multi-host runs in the first
    collective (one process exits, the others wait on it)."""
    import pytest as _pytest

    from galah_tpu.outputs import setup_outputs, validate_output_paths

    nonempty = tmp_path / "nonempty"
    nonempty.mkdir()
    (nonempty / "x").write_text("x")
    nested = tmp_path / "a" / "b" / "c"
    filedir = tmp_path / "iamadir"
    filedir.mkdir()

    cases = [
        # (kwargs, should_fail)
        ({"representative_fasta_directory": str(nonempty)}, True),
        ({"representative_fasta_directory": str(nested)}, False),
        ({"cluster_definition": str(filedir)}, True),
        ({"cluster_definition": str(tmp_path / "missing" / "f.tsv")},
         True),
        ({"cluster_definition": str(tmp_path / "ok.tsv")}, False),
    ]
    for kwargs, should_fail in cases:
        if should_fail:
            with _pytest.raises((OSError, ValueError)):
                validate_output_paths(**kwargs)
            with _pytest.raises((OSError, ValueError)):
                setup_outputs(**kwargs)
        else:
            validate_output_paths(**kwargs)  # must not raise
            setup_outputs(**kwargs)          # and setup agrees
            # reset for repeatability of the nested-dir case
            import shutil

            if "representative_fasta_directory" in kwargs:
                shutil.rmtree(tmp_path / "a")


@needs_reference_data
def test_platform_flag_forces_backend(tmp_path):
    """--platform cpu must win over any interpreter-level platform
    default (a sitecustomize pinning a device backend overrides the
    JAX_PLATFORMS env var, so the flag goes through jax.config, which
    that cannot override). Run in a subprocess with the test env's
    platform pins stripped so the interpreter default applies."""
    import subprocess
    import sys

    out = tmp_path / "dist.tsv"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    # Pin a CONFLICTING platform so the test is not vacuous on
    # CPU-only hosts: without the flag's jax.config override, cuda
    # (absent from this image) would fail backend init; the flag
    # must beat the env pin.
    env["JAX_PLATFORMS"] = "cuda"
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    code = (
        "import sys\n"
        "from galah_tpu.cli import main\n"
        f"rc = main(['dist', '--platform', 'cpu',\n"
        f"           '--genome-fasta-files', '{DATA}/set1/1mbp.fna',\n"
        f"           '{DATA}/set1/500kb.fna',\n"
        f"           '--output', '{out}'])\n"
        "import jax\n"
        "assert jax.default_backend() == 'cpu', jax.default_backend()\n"
        "sys.exit(rc)\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=420,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    ani = float(out.read_text().split("\t")[2])
    assert abs(ani - 0.9808188) < 5e-7


def test_platform_flag_bad_value_clean_error(tmp_path):
    """An unavailable --platform is a one-line user error, exit 1 —
    not a RuntimeError traceback at first device use."""
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    code = (
        "import sys\n"
        "from galah_tpu.cli import main\n"
        f"sys.exit(main(['dist', '--platform', 'cuda',\n"
        f"               '--genome-fasta-files', '{DATA}/set1/1mbp.fna',\n"
        f"               '{DATA}/set1/500kb.fna',\n"
        f"               '--output', '{tmp_path / 'd.tsv'}']))\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=420,
                          env=env)
    assert proc.returncode == 1, (proc.returncode, proc.stderr[-500:])
    assert "Traceback" not in proc.stderr
    assert "--platform cuda" in proc.stderr and "failed to initialize" in proc.stderr


# -- preemption / --resume -------------------------------------------
# These run on generated genomes, not the reference fixtures: the
# contract under test is the interruption protocol, not clustering.


def _tiny_genomes(tmp_path, n=4):
    import random as _random

    rng = _random.Random(7)
    paths = []
    base = [rng.choice("ACGT") for _ in range(5000)]
    for i in range(n):
        seq = list(base)
        for _ in range(i * 10):  # small divergence between genomes
            pos = rng.randrange(len(seq))
            seq[pos] = rng.choice("ACGT")
        p = tmp_path / f"g{i}.fna"
        p.write_text(">c\n" + "".join(seq) + "\n")
        paths.append(str(p))
    return paths


def test_resume_requires_checkpoint_dir(tmp_path):
    out = tmp_path / "c.tsv"
    rc = _run(["cluster", "--genome-fasta-files",
               *_tiny_genomes(tmp_path), "--resume",
               "--output-cluster-definition", str(out)])
    assert rc == 1


def test_resume_refuses_empty_checkpoint_dir(tmp_path):
    out = tmp_path / "c.tsv"
    rc = _run(["cluster", "--genome-fasta-files",
               *_tiny_genomes(tmp_path), "--resume",
               "--checkpoint-dir", str(tmp_path / "ck"),
               "--output-cluster-definition", str(out)])
    assert rc == 1  # no fingerprint to resume from


def test_preemption_exits_75_then_resume_completes(tmp_path,
                                                   monkeypatch):
    """A stop requested right after install preempts at the first safe
    boundary (exit 75, no output, interruption recorded); `--resume`
    then completes with the chain in the run report."""
    import json

    from galah_tpu.resilience import interrupt

    genomes = _tiny_genomes(tmp_path)
    out = tmp_path / "c.tsv"
    ck = tmp_path / "ck"
    report = tmp_path / "report.json"

    real_install = interrupt.install

    def install_and_stop():
        real_install()
        interrupt.request_stop("TEST")

    monkeypatch.setattr(interrupt, "install", install_and_stop)
    rc = _run(["cluster", "--genome-fasta-files", *genomes,
               "--checkpoint-dir", str(ck),
               "--output-cluster-definition", str(out),
               "--run-report", str(report)])
    assert rc == interrupt.EXIT_PREEMPTED == 75
    # preempted before write-outputs: the handle exists (setup_outputs
    # opens it up front) but no cluster rows were written
    assert not out.exists() or out.read_bytes() == b""
    rep = json.loads(report.read_text())
    assert rep["preemption"]["stop_requested"] is True
    assert rep["preemption"]["boundary"] is not None
    monkeypatch.undo()

    rc = _run(["cluster", "--genome-fasta-files", *genomes,
               "--resume", "--checkpoint-dir", str(ck),
               "--output-cluster-definition", str(out),
               "--run-report", str(report)])
    assert rc == 0
    assert out.exists()
    rep = json.loads(report.read_text())
    assert rep["preemption"]["resumed_from"] == str(ck)
    assert rep["preemption"]["prior_interruptions"] == 1

    # and the resumed output equals an uninterrupted run's
    out2 = tmp_path / "c2.tsv"
    rc = _run(["cluster", "--genome-fasta-files", *genomes,
               "--checkpoint-dir", str(tmp_path / "ck2"),
               "--output-cluster-definition", str(out2)])
    assert rc == 0
    assert out.read_bytes() == out2.read_bytes()
