"""Durable-write primitive (io/atomic.py) + cooperative interruption
(resilience/interrupt.py).

The all-or-nothing contract: a writer killed at ANY instant leaves a
durable artifact absent, fully old, or fully new — never torn. These
tests pin the framing format, the torn-tail recovery and self-healing,
the crash-debris sweep, the GALAH_FI filesystem fault kinds that fire
inside the primitives, and the signal → safe-boundary → exit-75
interruption protocol. The kill-anywhere end-to-end proof is
scripts/chaos_run.py / tests/test_chaos.py.
"""

import json
import os
import signal
import zlib

import numpy as np
import pytest

from galah_tpu.io import atomic
from galah_tpu.resilience import faults, interrupt


@pytest.fixture(autouse=True)
def _clean_injector(monkeypatch):
    monkeypatch.delenv("GALAH_FI", raising=False)
    faults.reset()
    yield
    faults.reset()


# -- whole-file writes ------------------------------------------------


def test_write_bytes_roundtrip_and_no_debris(tmp_path):
    p = str(tmp_path / "a.bin")
    atomic.write_bytes(p, b"hello")
    assert open(p, "rb").read() == b"hello"
    atomic.write_bytes(p, b"replaced")
    assert open(p, "rb").read() == b"replaced"
    assert [n for n in os.listdir(tmp_path) if n.endswith(".tmp")] == []


def test_write_json_sorted_and_newline_terminated(tmp_path):
    p = str(tmp_path / "r.json")
    atomic.write_json(p, {"b": 1, "a": 2})
    raw = open(p).read()
    assert raw.endswith("\n")
    assert json.loads(raw) == {"a": 2, "b": 1}
    assert raw.index('"a"') < raw.index('"b"')


def test_write_npz_roundtrip(tmp_path):
    p = str(tmp_path / "d.npz")
    atomic.write_npz(p, {"x": np.arange(4), "y": np.eye(2)})
    with np.load(p) as z:
        np.testing.assert_array_equal(z["x"], np.arange(4))
        np.testing.assert_array_equal(z["y"], np.eye(2))


def test_write_creates_parent_dirs(tmp_path):
    p = str(tmp_path / "deep" / "er" / "f.json")
    atomic.write_json(p, [1, 2])
    assert json.load(open(p)) == [1, 2]


# -- append framing ---------------------------------------------------


def test_frame_line_format_and_crc(tmp_path):
    line = atomic.frame_line({"k": "v"})
    assert line.endswith("\n")
    payload, sep, crc_hex = line.rstrip("\n").rpartition(
        atomic.FRAME_SEP)
    assert sep == atomic.FRAME_SEP
    assert json.loads(payload) == {"k": "v"}
    assert int(crc_hex, 16) == zlib.crc32(payload.encode()) & 0xFFFFFFFF


def test_frame_sep_is_not_a_splitlines_boundary():
    """Tooling reads these logs line-wise; the separator must not make
    str.splitlines see two lines per record (as \\x1e would)."""
    assert len(atomic.frame_line({"a": 1}).splitlines()) == 1


def test_append_read_roundtrip_in_order(tmp_path):
    p = str(tmp_path / "log.jsonl")
    for i in range(5):
        atomic.append_jsonl(p, {"i": i})
    records, bad = atomic.read_jsonl(p)
    assert bad == 0
    assert [r["i"] for r in records] == list(range(5))


def test_read_jsonl_missing_file_is_empty(tmp_path):
    assert atomic.read_jsonl(str(tmp_path / "nope.jsonl")) == ([], 0)


def test_read_jsonl_rejects_flipped_byte(tmp_path):
    p = str(tmp_path / "log.jsonl")
    atomic.append_jsonl(p, {"i": 0})
    atomic.append_jsonl(p, {"i": 1})
    raw = bytearray(open(p, "rb").read())
    raw[2] ^= 0xFF  # corrupt record 0's payload
    open(p, "wb").write(bytes(raw))
    records, bad = atomic.read_jsonl(p)
    assert bad == 1
    assert [r["i"] for r in records] == [1]


def test_read_jsonl_accepts_legacy_unframed_lines(tmp_path):
    p = str(tmp_path / "old.jsonl")
    with open(p, "w") as f:
        f.write('{"legacy": true}\n')
    atomic.append_jsonl(p, {"legacy": False})
    records, bad = atomic.read_jsonl(p)
    assert bad == 0
    assert [r["legacy"] for r in records] == [True, False]


def test_append_heals_torn_tail(tmp_path):
    """A record appended after a torn tail must itself stay intact:
    the torn bytes are confined to their own (rejected) line."""
    p = str(tmp_path / "log.jsonl")
    atomic.append_jsonl(p, {"i": 0})
    with open(p, "ab") as f:  # simulate a kill mid-append: no newline
        f.write(atomic.frame_line({"i": 1}).encode()[:4])
    atomic.append_jsonl(p, {"i": 2})
    records, bad = atomic.read_jsonl(p)
    assert bad == 1
    assert [r["i"] for r in records] == [0, 2]


# -- crash-debris sweep -----------------------------------------------


def test_sweep_tmp_single_owner_removes_all(tmp_path):
    (tmp_path / "x.json.abc123.tmp").write_bytes(b"debris")
    (tmp_path / "keep.json").write_bytes(b"{}")
    assert atomic.sweep_tmp(str(tmp_path)) == 1
    assert (tmp_path / "keep.json").exists()
    assert not (tmp_path / "x.json.abc123.tmp").exists()


def test_sweep_tmp_age_gate_spares_young_files(tmp_path):
    (tmp_path / "young.tmp").write_bytes(b"live writer")
    assert atomic.sweep_tmp(str(tmp_path),
                            max_age_s=atomic.SHARED_TMP_MAX_AGE_S) == 0
    old = tmp_path / "old.tmp"
    old.write_bytes(b"stale")
    os.utime(old, (1, 1))
    assert atomic.sweep_tmp(str(tmp_path),
                            max_age_s=atomic.SHARED_TMP_MAX_AGE_S) == 1
    assert (tmp_path / "young.tmp").exists()


def test_sweep_tmp_missing_dir_is_zero(tmp_path):
    assert atomic.sweep_tmp(str(tmp_path / "absent")) == 0


# -- filesystem fault kinds -------------------------------------------


@pytest.mark.fault_injection
def test_enospc_fault_leaves_target_untouched(tmp_path, monkeypatch):
    p = str(tmp_path / "a.json")
    atomic.write_json(p, {"v": 1})
    monkeypatch.setenv(
        "GALAH_FI", "site=io.atomic;kind=enospc;prob=1;seed=1")
    faults.reset()
    with pytest.raises(OSError) as ei:
        atomic.write_json(p, {"v": 2})
    assert ei.value.errno == 28  # ENOSPC
    assert json.load(open(p)) == {"v": 1}  # old content fully intact


@pytest.mark.fault_injection
def test_eio_fault_on_append_keeps_log_readable(tmp_path, monkeypatch):
    p = str(tmp_path / "log.jsonl")
    atomic.append_jsonl(p, {"i": 0})
    monkeypatch.setenv(
        "GALAH_FI", "site=io.atomic;kind=eio;prob=1;seed=1")
    faults.reset()
    with pytest.raises(OSError) as ei:
        atomic.append_jsonl(p, {"i": 1})
    assert ei.value.errno == 5  # EIO
    records, bad = atomic.read_jsonl(p)
    assert [r["i"] for r in records] == [0] and bad == 0


@pytest.mark.fault_injection
def test_torn_write_fault_leaves_sweepable_debris(tmp_path,
                                                  monkeypatch):
    p = str(tmp_path / "a.json")
    atomic.write_json(p, {"v": 1})
    monkeypatch.setenv(
        "GALAH_FI", "site=io.atomic;kind=torn-write;prob=1;seed=1;max=1")
    faults.reset()
    with pytest.raises(OSError):
        atomic.write_json(p, {"v": 2})
    assert json.load(open(p)) == {"v": 1}
    debris = [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
    assert len(debris) == 1  # the half-written tmp a real kill leaves
    assert atomic.sweep_tmp(str(tmp_path)) == 1
    atomic.write_json(p, {"v": 3})  # max=1: injector is spent
    assert json.load(open(p)) == {"v": 3}


@pytest.mark.fault_injection
def test_torn_append_recovered_by_next_append(tmp_path, monkeypatch):
    p = str(tmp_path / "log.jsonl")
    atomic.append_jsonl(p, {"i": 0})
    monkeypatch.setenv(
        "GALAH_FI", "site=io.atomic;kind=torn-write;prob=1;seed=1;max=1")
    faults.reset()
    with pytest.raises(OSError):
        atomic.append_jsonl(p, {"i": 1})
    monkeypatch.delenv("GALAH_FI")
    faults.reset()
    atomic.append_jsonl(p, {"i": 2})
    records, bad = atomic.read_jsonl(p)
    assert bad == 1  # the torn half-record, rejected by its checksum
    assert [r["i"] for r in records] == [0, 2]


@pytest.mark.fault_injection
def test_slow_io_fault_succeeds_after_delay(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "GALAH_FI",
        "site=io.atomic;kind=slow-io;prob=1;seed=1;hang=0.01;max=1")
    faults.reset()
    p = str(tmp_path / "a.json")
    atomic.write_json(p, {"v": 1})  # delayed, not failed
    assert json.load(open(p)) == {"v": 1}


# -- cooperative interruption -----------------------------------------


@pytest.fixture(autouse=True)
def _clean_interrupt():
    interrupt.reset()
    yield
    interrupt.uninstall()
    interrupt.reset()


def test_check_passes_when_no_stop_requested():
    interrupt.check("round-boundary")  # no raise
    assert not interrupt.stop_requested()


def test_request_stop_raises_at_next_boundary():
    interrupt.request_stop("TEST")
    with pytest.raises(interrupt.PreemptionRequested) as ei:
        interrupt.check("greedy-round-saved")
    assert ei.value.boundary == "greedy-round-saved"
    assert ei.value.signame == "TEST"


def test_sigterm_sets_flag_cooperatively():
    interrupt.install()
    os.kill(os.getpid(), signal.SIGTERM)
    assert interrupt.stop_requested()
    with pytest.raises(interrupt.PreemptionRequested) as ei:
        interrupt.check("distances-saved")
    assert ei.value.signame == "SIGTERM"
    snap = interrupt.snapshot()
    assert snap["signals"] == ["SIGTERM"]
    assert snap["boundary"] == "distances-saved"


def test_uninstall_restores_previous_handlers():
    prev = signal.getsignal(signal.SIGTERM)
    interrupt.install()
    assert signal.getsignal(signal.SIGTERM) is not prev
    interrupt.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_snapshot_records_resume_chain():
    interrupt.note_resume("/ck/dir", prior_interruptions=2)
    snap = interrupt.snapshot()
    assert snap["resumed_from"] == "/ck/dir"
    assert snap["prior_interruptions"] == 2
    interrupt.reset()
    assert interrupt.snapshot()["resumed_from"] is None


def test_exit_code_is_ex_tempfail():
    assert interrupt.EXIT_PREEMPTED == 75
