"""Bounded IO prefetch iterator (io/prefetch.py)."""

import threading
import time

import pytest

from galah_tpu.io.prefetch import iter_prefetched


def test_order_and_completeness():
    paths = [f"p{i}" for i in range(17)]
    out = list(iter_prefetched(paths, lambda p: p.upper(), depth=3))
    assert [p for p, _ in out] == paths
    assert [v for _, v in out] == [p.upper() for p in paths]


def test_bounded_lookahead():
    """Never more than `depth` loads in flight beyond consumption."""
    lock = threading.Lock()
    state = {"loaded": 0, "consumed": 0, "max_ahead": 0}

    def load(p):
        with lock:
            state["loaded"] += 1
            ahead = state["loaded"] - state["consumed"]
            state["max_ahead"] = max(state["max_ahead"], ahead)
        return p

    for p, _ in iter_prefetched([str(i) for i in range(20)], load,
                                depth=2):
        time.sleep(0.001)
        state["consumed"] += 1
    assert state["loaded"] == 20
    assert state["max_ahead"] <= 3  # depth + the one being consumed


def test_exception_surfaces_at_failing_item():
    def load(p):
        if p == "bad":
            raise ValueError("boom")
        return p

    it = iter_prefetched(["a", "bad", "c"], load, depth=2)
    assert next(it)[0] == "a"
    with pytest.raises(ValueError, match="boom"):
        next(it)


def test_empty():
    assert list(iter_prefetched([], lambda p: p)) == []


def test_iter_batches_budget_and_cap():
    from galah_tpu.io.prefetch import iter_batches

    items = [(f"p{i}", i) for i in range(10)]
    # budget 5 with sizes 0..9: greedy accumulate-until-total>=budget
    out = list(iter_batches(iter(items), lambda v: v, budget=5))
    assert [len(b) for b in out] == [4, 2, 1, 1, 1, 1]
    assert [v for b in out for _, v in b] == list(range(10))

    # max_items cap
    out = list(iter_batches(iter(items), lambda v: 0, budget=10**9,
                            max_items=4))
    assert [len(b) for b in out] == [4, 4, 2]

    # empty stream
    assert list(iter_batches(iter([]), lambda v: v, budget=1)) == []


def test_process_stream_workers_parity():
    from galah_tpu.io.prefetch import process_stream

    items = [(f"p{i}", i) for i in range(17)]
    serial = dict(process_stream(
        iter(items), lambda v: 1, 10**9,
        batch_fn=None, single_fn=lambda p, v: v * v, batched=False))
    threaded = dict(process_stream(
        iter(items), lambda v: 1, 10**9,
        batch_fn=None, single_fn=lambda p, v: v * v, batched=False,
        workers=4))
    assert serial == threaded == {f"p{i}": i * i for i in range(17)}


def test_process_stream_workers_propagates_errors():
    from galah_tpu.io.prefetch import process_stream

    def boom(p, v):
        if v == 5:
            raise RuntimeError("x")
        return v

    items = [(f"p{i}", i) for i in range(8)]
    try:
        list(process_stream(iter(items), lambda v: 1, 10**9, None,
                            boom, batched=False, workers=3))
    except RuntimeError as e:
        assert str(e) == "x"
    else:
        raise AssertionError("expected RuntimeError")


def test_live_stream_survives_pool_growth():
    """A partially-consumed stream holds the shared pool it started
    on; a later, larger request must not shut that pool down under it
    (regression: mid-stream RuntimeError after replacement)."""
    import time

    from galah_tpu.io.prefetch import _shared_pool, iter_prefetched

    def slow(p):
        time.sleep(0.005)
        return p.upper()

    gen = iter_prefetched([f"p{i}" for i in range(8)], slow, depth=2)
    assert next(gen) == ("p0", "P0")
    _shared_pool(64)  # force a replacement while gen is live
    assert list(gen) == [(f"p{i}", f"P{i}") for i in range(1, 8)]
