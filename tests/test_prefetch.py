"""Bounded IO prefetch iterator (io/prefetch.py)."""

import threading
import time

import pytest

from galah_tpu.io.prefetch import iter_prefetched


def test_order_and_completeness():
    paths = [f"p{i}" for i in range(17)]
    out = list(iter_prefetched(paths, lambda p: p.upper(), depth=3))
    assert [p for p, _ in out] == paths
    assert [v for _, v in out] == [p.upper() for p in paths]


def test_bounded_lookahead():
    """Never more than `depth` loads in flight beyond consumption."""
    lock = threading.Lock()
    state = {"loaded": 0, "consumed": 0, "max_ahead": 0}

    def load(p):
        with lock:
            state["loaded"] += 1
            ahead = state["loaded"] - state["consumed"]
            state["max_ahead"] = max(state["max_ahead"], ahead)
        return p

    for p, _ in iter_prefetched([str(i) for i in range(20)], load,
                                depth=2):
        time.sleep(0.001)
        state["consumed"] += 1
    assert state["loaded"] == 20
    assert state["max_ahead"] <= 3  # depth + the one being consumed


def test_exception_surfaces_at_failing_item():
    def load(p):
        if p == "bad":
            raise ValueError("boom")
        return p

    it = iter_prefetched(["a", "bad", "c"], load, depth=2)
    assert next(it)[0] == "a"
    with pytest.raises(ValueError, match="boom"):
        next(it)


def test_empty():
    assert list(iter_prefetched([], lambda p: p)) == []


def test_iter_batches_budget_and_cap():
    from galah_tpu.io.prefetch import iter_batches

    items = [(f"p{i}", i) for i in range(10)]
    # budget 5 with sizes 0..9: greedy accumulate-until-total>=budget
    out = list(iter_batches(iter(items), lambda v: v, budget=5))
    assert [len(b) for b in out] == [4, 2, 1, 1, 1, 1]
    assert [v for b in out for _, v in b] == list(range(10))

    # max_items cap
    out = list(iter_batches(iter(items), lambda v: 0, budget=10**9,
                            max_items=4))
    assert [len(b) for b in out] == [4, 4, 2]

    # empty stream
    assert list(iter_batches(iter([]), lambda v: v, budget=1)) == []


def test_process_stream_workers_parity():
    from galah_tpu.io.prefetch import process_stream

    items = [(f"p{i}", i) for i in range(17)]
    serial = dict(process_stream(
        iter(items), lambda v: 1, 10**9,
        batch_fn=None, single_fn=lambda p, v: v * v, batched=False))
    threaded = dict(process_stream(
        iter(items), lambda v: 1, 10**9,
        batch_fn=None, single_fn=lambda p, v: v * v, batched=False,
        workers=4))
    assert serial == threaded == {f"p{i}": i * i for i in range(17)}


def test_process_stream_workers_propagates_errors():
    from galah_tpu.io.prefetch import process_stream

    def boom(p, v):
        if v == 5:
            raise RuntimeError("x")
        return v

    items = [(f"p{i}", i) for i in range(8)]
    try:
        list(process_stream(iter(items), lambda v: 1, 10**9, None,
                            boom, batched=False, workers=3))
    except RuntimeError as e:
        assert str(e) == "x"
    else:
        raise AssertionError("expected RuntimeError")


def test_abandoned_generator_settles_inflight_loads():
    """Closing a partially-consumed stream waits out running loads and
    cancels queued ones (_settle) — after close() returns, no load_fn
    is racing with the caller's cleanup (e.g. a temp-dir removal after
    the exception that abandoned the stream)."""
    lock = threading.Lock()
    running = set()
    started = []

    def load(p):
        with lock:
            running.add(p)
            started.append(p)
        time.sleep(0.02)
        with lock:
            running.discard(p)
        return p

    gen = iter_prefetched([f"p{i}" for i in range(10)], load, depth=3)
    assert next(gen) == ("p0", "p0")
    gen.close()
    with lock:
        assert not running  # nothing still executing
    n = len(started)
    time.sleep(0.05)
    assert len(started) == n  # nothing new started after close


def test_settle_swallows_worker_errors_on_abandon():
    """A load that fails while the generator is being abandoned is
    absorbed by _settle (there is no consumer left to surface it to) —
    close() must not raise."""
    def load(p):
        if p != "p0":
            time.sleep(0.005)
            raise ValueError(p)
        return p

    gen = iter_prefetched([f"p{i}" for i in range(6)], load, depth=2)
    assert next(gen) == ("p0", "p0")
    gen.close()  # in-flight failures absorbed, not raised


def test_process_stream_abandoned_settles_workers():
    """Same contract for the worker-pool branch of process_stream: an
    abandoned stream leaves no single_fn running or newly starting."""
    from galah_tpu.io.prefetch import process_stream

    lock = threading.Lock()
    state = {"running": 0, "started": 0}

    def work(p, v):
        with lock:
            state["running"] += 1
            state["started"] += 1
        time.sleep(0.02)
        with lock:
            state["running"] -= 1
        return v

    items = [(f"p{i}", i) for i in range(12)]
    gen = process_stream(iter(items), lambda v: 1, 10**9, None, work,
                         batched=False, workers=3)
    assert next(gen) == ("p0", 0)
    gen.close()
    with lock:
        assert state["running"] == 0
    n = state["started"]
    time.sleep(0.05)
    assert state["started"] == n


def test_live_stream_survives_pool_growth():
    """A partially-consumed stream holds the shared pool it started
    on; a later, larger request must not shut that pool down under it
    (regression: mid-stream RuntimeError after replacement)."""
    import time

    from galah_tpu.io.prefetch import _shared_pool, iter_prefetched

    def slow(p):
        time.sleep(0.005)
        return p.upper()

    gen = iter_prefetched([f"p{i}" for i in range(8)], slow, depth=2)
    assert next(gen) == ("p0", "P0")
    _shared_pool(64)  # force a replacement while gen is live
    assert list(gen) == [(f"p{i}", f"P{i}") for i in range(1, 8)]
