"""ANI-value accuracy of the fragment-containment kernel.

Round-1 review finding: the kernel's calibration was asserted, not
tested — clustering outcomes were pinned but no test checked that a
planted ANI is MEASURED back within tolerance. These tests plant known
mutation rates / aligned fractions in synthetic genomes and assert the
kernel recovers them, the accuracy class the reference gets from skani's
learned ANI (reference: src/skani.rs:148-163) and fastANI's fragment
mapping (reference: src/fastani.rs:31-73).
"""

import numpy as np
import pytest

from galah_tpu.ops import fragment_ani
from galah_tpu.io.fasta import Genome, GenomeStats

K = 15
L = 500_000


def _genome(codes: np.ndarray, path: str) -> Genome:
    return Genome(
        path=path, codes=codes.astype(np.uint8),
        contig_offsets=np.array([0, codes.shape[0]], dtype=np.int64),
        stats=GenomeStats(1, 0, codes.shape[0]))


def _mutate(codes: np.ndarray, rate: float, rng) -> tuple[np.ndarray, int]:
    """Point-substitute at `rate`; returns (mutant, n_actual_sites)."""
    sites = rng.random(codes.shape[0]) < rate
    n = int(sites.sum())
    out = codes.copy()
    out[sites] = (out[sites] + rng.integers(1, 4, size=n)) % 4
    return out, n


@pytest.mark.parametrize("rate", [0.005, 0.01, 0.03, 0.05, 0.10])
def test_measured_ani_matches_planted_mutation_rate(rate):
    """Measured ANI must track the realized substitution rate within
    0.3 percentage points across the 90-99.5% range."""
    rng = np.random.default_rng(int(rate * 10_000))
    base = rng.integers(0, 4, size=L).astype(np.uint8)
    mut, n_sites = _mutate(base, rate, rng)
    planted_ani = 1.0 - n_sites / L

    pa = fragment_ani.build_profile(_genome(base, "a"), k=K, fraglen=3000)
    pb = fragment_ani.build_profile(_genome(mut, "b"), k=K, fraglen=3000)
    ani, ab, ba = fragment_ani.bidirectional_ani(
        pa, pb, min_aligned_frac=0.15)
    assert ani is not None
    assert abs(ani - planted_ani) < 0.003, (
        f"planted {planted_ani:.4f}, measured {ani:.4f}")
    # fully homologous pair: both directions essentially fully aligned
    assert ab.aligned_fraction > 0.95
    assert ba.aligned_fraction > 0.95


@pytest.mark.parametrize("frac", [0.3, 0.6, 0.9])
def test_aligned_fraction_matches_planted(frac):
    """A genome sharing `frac` of its span with the reference (the rest
    unrelated random sequence) must measure aligned_fraction ~= frac."""
    rng = np.random.default_rng(int(frac * 100))
    base = rng.integers(0, 4, size=L).astype(np.uint8)
    n_shared = int(L * frac)
    # light mutation on the shared part so it's homologous-not-identical
    shared, _ = _mutate(base[:n_shared], 0.02, rng)
    unrelated = rng.integers(0, 4, size=L - n_shared).astype(np.uint8)
    query = np.concatenate([shared, unrelated])

    pa = fragment_ani.build_profile(_genome(query, "q"), k=K, fraglen=3000)
    pb = fragment_ani.build_profile(_genome(base, "r"), k=K, fraglen=3000)
    _, ab, _ = fragment_ani.bidirectional_ani(pa, pb,
                                              min_aligned_frac=0.0)
    assert abs(ab.aligned_fraction - frac) < 0.04, (
        f"planted AF {frac}, measured {ab.aligned_fraction:.3f}")


def test_gate_flips_with_min_aligned_fraction():
    """The bidirectional gate (reference: src/fastani.rs:56-65): a pair
    at 60% aligned fraction passes a 0.5 gate and fails a 0.8 gate."""
    rng = np.random.default_rng(77)
    base = rng.integers(0, 4, size=200_000).astype(np.uint8)
    shared, _ = _mutate(base[:120_000], 0.02, rng)
    unrelated = rng.integers(0, 4, size=80_000).astype(np.uint8)
    query = np.concatenate([shared, unrelated])

    pa = fragment_ani.build_profile(_genome(query, "q"), k=K, fraglen=3000)
    pb = fragment_ani.build_profile(_genome(base, "r"), k=K, fraglen=3000)
    pass_lo, _, _ = fragment_ani.bidirectional_ani(
        pa, pb, min_aligned_frac=0.5)
    pass_hi, _, _ = fragment_ani.bidirectional_ani(
        pa, pb, min_aligned_frac=0.8)
    assert pass_lo is not None
    assert pass_hi is None


def test_unrelated_genomes_measure_no_ani():
    rng = np.random.default_rng(5)
    a = rng.integers(0, 4, size=100_000).astype(np.uint8)
    b = rng.integers(0, 4, size=100_000).astype(np.uint8)
    pa = fragment_ani.build_profile(_genome(a, "a"), k=K, fraglen=3000)
    pb = fragment_ani.build_profile(_genome(b, "b"), k=K, fraglen=3000)
    ani, ab, ba = fragment_ani.bidirectional_ani(
        pa, pb, min_aligned_frac=0.15)
    assert ani is None
    assert ab.frags_matching == 0 and ba.frags_matching == 0


@pytest.mark.parametrize("algo", ["murmur3", "tpufast"])
@pytest.mark.parametrize("c", [16, 125])
def test_subsampled_ani_tracks_planted_rate(c, algo):
    """FracMinHash subsampling (--ani-subsample) must keep the measured
    ANI within 0.5pp of the planted rate — the accuracy class of the
    reference's skani, which runs at c=125 (reference:
    src/skani.rs:159-161). Both profile hashes must hold the bound
    (--hash-algorithm selects the fragment-profile hash too)."""
    rng = np.random.default_rng(c)
    base = rng.integers(0, 4, size=L).astype(np.uint8)
    mut, n_sites = _mutate(base, 0.03, rng)
    planted = 1.0 - n_sites / L

    pa = fragment_ani.build_profile(_genome(base, "a"), k=K,
                                    fraglen=3000, subsample_c=c,
                                    hash_algorithm=algo)
    pb = fragment_ani.build_profile(_genome(mut, "b"), k=K,
                                    fraglen=3000, subsample_c=c,
                                    hash_algorithm=algo)
    ani, ab, ba = fragment_ani.bidirectional_ani(
        pa, pb, min_aligned_frac=0.15)
    assert ani is not None
    assert abs(ani - planted) < 0.005, (c, ani, planted)
    assert ab.aligned_fraction > 0.9
    # the subsampled reference set really is ~c-fold smaller
    assert pa.ref_set.shape[0] < (L / c) * 1.3


def test_subsampled_cli_keeps_golden_clusters(tmp_path):
    """--ani-subsample 16 must reproduce the reference's 4-MAG golden
    composition (clusters are robust to the per-window variance)."""
    import pytest as _pytest

    ref = "/root/reference/tests/data/abisko4"
    import os
    if not os.path.isdir(ref):
        _pytest.skip("reference fixtures unavailable")
    from galah_tpu.cli import main

    paths = [f"{ref}/{m}" for m in (
        "73.20120800_S1X.13.fna", "73.20120600_S2D.19.fna",
        "73.20120700_S3X.12.fna", "73.20110800_S2D.13.fna")]
    out = tmp_path / "c.tsv"
    rc = main(["cluster", "--genome-fasta-files", *paths,
               "--precluster-method", "finch", "--cluster-method",
               "skani", "--ani", "99", "--ani-subsample", "16",
               "--output-cluster-definition", str(out)])
    assert rc == 0
    clusters = {}
    for line in out.read_text().splitlines():
        rep, member = line.split("\t")
        clusters.setdefault(rep, set()).add(paths.index(member))
    got = sorted(clusters.values(), key=lambda s: -len(s))
    assert got == [{0, 1, 3}, {2}]
