"""FASTA ingestion and genome-stats goldens.

Golden values come from the reference's inline tests
(reference: src/genome_stats.rs:61-87).
"""

import numpy as np

from galah_tpu.io import read_genome
from galah_tpu.io.fasta import calculate_genome_stats


def test_golden_stats_abisko4(ref_data):
    stats = calculate_genome_stats(
        str(ref_data / "abisko4" / "73.20110600_S2D.10.fna"))
    assert stats.num_contigs == 161
    assert stats.num_ambiguous_bases == 6506
    assert stats.n50 == 8289


def test_single_contig_n50(tmp_path):
    p = tmp_path / "one.fna"
    p.write_text(">c1\n" + "ACGT" * 25 + "\n")
    stats = calculate_genome_stats(str(p))
    assert stats.num_contigs == 1
    assert stats.num_ambiguous_bases == 0
    assert stats.n50 == 100


def test_codes_and_offsets(tmp_path):
    p = tmp_path / "two.fna"
    p.write_text(">a\nACGTN\nacgt\n>b desc\nTTTT\n")
    g = read_genome(str(p))
    assert g.stats.num_contigs == 2
    assert g.stats.num_ambiguous_bases == 1
    np.testing.assert_array_equal(g.contig_offsets, [0, 9, 13])
    np.testing.assert_array_equal(
        g.codes, [0, 1, 2, 3, 255, 0, 1, 2, 3, 3, 3, 3, 3])


def test_gzip_roundtrip(tmp_path):
    import gzip

    p = tmp_path / "g.fna.gz"
    with gzip.open(p, "wt") as fh:
        fh.write(">a\nACGTACGT\n")
    g = read_genome(str(p))
    assert g.length == 8
    assert g.stats.n50 == 8
