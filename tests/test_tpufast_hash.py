"""Statistical validation of the tpufast sketch hash.

tpufast replaces murmur3's 12 u64 multiplies per k-mer with a
multiply-free shift-add mixer (the TPU VPU has no fast integer
multiply; see ops/hashing._tpufast_mix). MinHash/HLL only require a
uniform ranking hash, so the quality bar is statistical, not
bit-parity: uniformity, avalanche, injectivity, and sketch-level ANI
accuracy equal to the murmur path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from galah_tpu.ops import hashing
from galah_tpu.ops.minhash import sketch_genome_device, sketch_matrix
from galah_tpu.ops.minhash_np import mash_ani
from galah_tpu.io.fasta import Genome, GenomeStats


def _genome(codes, path="g"):
    return Genome(
        path=path, codes=codes.astype(np.uint8),
        contig_offsets=np.array([0, codes.shape[0]], dtype=np.int64),
        stats=GenomeStats(1, 0, codes.shape[0]))


def _hashes(codes, algo, k=21):
    out = []
    g = _genome(codes)
    for h, _pos, n_new in hashing.iter_chunk_hashes(
            g.codes, g.contig_offsets, k=k, chunk=1 << 18, algo=algo):
        out.append(np.asarray(h)[:n_new])
    flat = np.concatenate(out)
    return flat[flat != np.uint64(hashing.HASH_SENTINEL)]


def test_bit_balance_and_collisions():
    rng = np.random.default_rng(0)
    codes = rng.integers(0, 4, size=100_000).astype(np.uint8)
    h = _hashes(codes, "tpufast")
    # each output bit should be ~50/50 over ~100k structured inputs
    bits = ((h[:, None] >> np.arange(64, dtype=np.uint64)) & 1).mean(0)
    assert float(bits.min()) > 0.47 and float(bits.max()) < 0.53, bits
    # the mixer is a bijection on u64: distinct canonical k-mers must
    # produce distinct hashes
    # (count distinct canonical kmers via the murmur path as reference)
    h_m = _hashes(codes, "murmur3")
    assert np.unique(h).shape[0] == np.unique(h_m).shape[0]


def test_top_bits_uniform():
    """Bottom-k MinHash ranks by value: the LOW end of the hash range
    must fill uniformly (chi-square over 256 buckets of the top byte)."""
    rng = np.random.default_rng(3)
    codes = rng.integers(0, 4, size=200_000).astype(np.uint8)
    h = _hashes(codes, "tpufast")
    buckets = np.bincount((h >> np.uint64(56)).astype(np.int64),
                          minlength=256)
    expected = h.shape[0] / 256.0
    chi2 = float(((buckets - expected) ** 2 / expected).sum())
    # df=255; mean 255, std ~22.6 — allow 6 sigma
    assert chi2 < 255 + 6 * 23, chi2


def test_avalanche_single_base_change():
    """Changing one base must decorrelate the affected hashes (~32 of
    64 bits flip on average)."""
    rng = np.random.default_rng(7)
    codes = rng.integers(0, 4, size=50_000).astype(np.uint8)
    mutated = codes.copy()
    mutated[25_000] = (mutated[25_000] + 1) % 4
    h0 = _hashes(codes, "tpufast")
    h1 = _hashes(mutated, "tpufast")
    diff = h0 != h1
    changed0 = h0[diff]
    changed1 = h1[diff]
    assert changed0.shape[0] >= 15  # ~21 windows touch the site
    flips = np.unpackbits(
        (changed0 ^ changed1).view(np.uint8)).sum() / changed0.shape[0]
    assert 24 < flips < 40, flips


@pytest.mark.parametrize("rate", [0.01, 0.05])
def test_sketch_ani_accuracy_matches_murmur(rate):
    """Mash ANI estimated via tpufast sketches must match the planted
    mutation rate as well as the murmur3 sketches do."""
    rng = np.random.default_rng(int(rate * 1000))
    L = 400_000
    base = rng.integers(0, 4, size=L).astype(np.uint8)
    sites = rng.random(L) < rate
    mut = base.copy()
    mut[sites] = (mut[sites] + rng.integers(
        1, 4, size=int(sites.sum()))) % 4
    planted = 1.0 - sites.mean()

    for algo in ("tpufast", "murmur3"):
        s1 = sketch_genome_device(_genome(base, "a"), algo=algo)
        s2 = sketch_genome_device(_genome(mut, "b"), algo=algo)
        est = mash_ani(s1, s2)
        assert abs(est - planted) < 0.006, (algo, est, planted)
