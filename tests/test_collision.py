"""Inverted-index collision counter: exactness against brute force,
incl. the big-run dedup path and chunked compaction."""

import numpy as np

from galah_tpu.ops.collision import (
    _BIG_RUN,
    _COMPACT_EVERY,
    collision_pair_counts,
)
from galah_tpu.ops.constants import SENTINEL


def _brute(mat, lens):
    n = mat.shape[0]
    sets = [set(mat[i, : lens[i]].tolist()) for i in range(n)]
    out = {}
    for i in range(n):
        for j in range(i + 1, n):
            c = len(sets[i] & sets[j])
            if c:
                out[(i, j)] = c
    return out


def test_exact_vs_brute_force_mixed_runs():
    rng = np.random.default_rng(61)
    n, width = 300, 40
    mat = np.full((n, width), np.uint64(SENTINEL), dtype=np.uint64)
    lens = np.zeros(n, dtype=np.int64)
    shared_big = np.sort(rng.choice(1 << 30, size=width,
                                    replace=False)).astype(np.uint64)
    for i in range(n):
        if i < 100:  # big near-duplicate cluster (runs ~100 > _BIG_RUN)
            row = shared_big.copy()
            row[rng.integers(0, width)] = rng.integers(
                1 << 40, 1 << 41, dtype=np.uint64)
        else:  # random small-collision rows over a modest space
            row = np.sort(rng.choice(1 << 12, size=width,
                                     replace=False)).astype(np.uint64)
        row = np.unique(row)
        mat[i, : row.shape[0]] = row
        lens[i] = row.shape[0]
    assert 100 > _BIG_RUN
    pi, pj, counts = collision_pair_counts(mat, lens)
    got = {(int(a), int(b)): int(c) for a, b, c in zip(pi, pj, counts)}
    assert got == _brute(mat, lens)


def test_compaction_threshold_is_exercised(monkeypatch):
    """Force tiny compaction chunks; results stay exact. Pins the
    numpy reference path directly — collision_pair_counts auto-routes
    to the C counter when it builds, which never reads
    _COMPACT_EVERY."""
    import galah_tpu.ops.collision as col

    monkeypatch.setattr(col, "_COMPACT_EVERY", 16)
    rng = np.random.default_rng(63)
    n, width = 120, 24
    mat = np.stack([
        np.sort(rng.choice(1 << 10, size=width,
                           replace=False)).astype(np.uint64)
        for _ in range(n)
    ])
    lens = np.full(n, width, dtype=np.int64)
    pi, pj, counts = col._collision_pair_counts_np(mat, lens)
    got = {(int(a), int(b)): int(c) for a, b, c in zip(pi, pj, counts)}
    assert got == _brute(mat, lens)
    assert _COMPACT_EVERY > 16  # the real threshold is untouched


def test_threshold_sweep_sparse_equals_dense(monkeypatch):
    """Sparse screened threshold_pairs_c equals dense across a sweep of
    thresholds on mixed family/ragged/empty sketches."""
    import pytest

    cps = pytest.importorskip("galah_tpu.ops._cpairstats")

    rng = np.random.default_rng(71)
    n, k_sketch = 1050, 48
    n_fam = 70
    base = rng.integers(0, 1 << 62, size=(n_fam, k_sketch),
                        dtype=np.uint64)
    mat = np.empty((n, k_sketch), dtype=np.uint64)
    for i in range(n):
        row = base[i % n_fam].copy()
        n_mut = int(rng.integers(0, 25))
        idx = rng.choice(k_sketch, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
        row.sort()
        mat[i] = row
    mat[3, 10:] = np.uint64(SENTINEL)   # ragged
    mat[9] = np.uint64(SENTINEL)        # empty
    mat.sort(axis=1)

    for thr in (0.90, 0.95, 0.975, 0.99):
        sparse = cps.threshold_pairs_c(mat, k_sketch, 21, thr)
        monkeypatch.setenv("GALAH_TPU_DENSE_PAIRS", "1")
        dense = cps.threshold_pairs_c(mat, k_sketch, 21, thr)
        monkeypatch.delenv("GALAH_TPU_DENSE_PAIRS")
        assert sparse == dense, thr
