"""Inverted-index collision counter: exactness against brute force,
incl. the big-run dedup path and chunked compaction."""

import numpy as np

from galah_tpu.ops.collision import (
    _BIG_RUN,
    _COMPACT_EVERY,
    collision_pair_counts,
)
from galah_tpu.ops.constants import SENTINEL


def _brute(mat, lens):
    n = mat.shape[0]
    sets = [set(mat[i, : lens[i]].tolist()) for i in range(n)]
    out = {}
    for i in range(n):
        for j in range(i + 1, n):
            c = len(sets[i] & sets[j])
            if c:
                out[(i, j)] = c
    return out


def test_exact_vs_brute_force_mixed_runs():
    rng = np.random.default_rng(61)
    n, width = 300, 40
    mat = np.full((n, width), np.uint64(SENTINEL), dtype=np.uint64)
    lens = np.zeros(n, dtype=np.int64)
    shared_big = np.sort(rng.choice(1 << 30, size=width,
                                    replace=False)).astype(np.uint64)
    for i in range(n):
        if i < 100:  # big near-duplicate cluster (runs ~100 > _BIG_RUN)
            row = shared_big.copy()
            row[rng.integers(0, width)] = rng.integers(
                1 << 40, 1 << 41, dtype=np.uint64)
        else:  # random small-collision rows over a modest space
            row = np.sort(rng.choice(1 << 12, size=width,
                                     replace=False)).astype(np.uint64)
        row = np.unique(row)
        mat[i, : row.shape[0]] = row
        lens[i] = row.shape[0]
    assert 100 > _BIG_RUN
    pi, pj, counts = collision_pair_counts(mat, lens)
    got = {(int(a), int(b)): int(c) for a, b, c in zip(pi, pj, counts)}
    assert got == _brute(mat, lens)


def test_compaction_threshold_is_exercised(monkeypatch):
    """Force tiny compaction chunks; results stay exact."""
    import galah_tpu.ops.collision as col

    monkeypatch.setattr(col, "_COMPACT_EVERY", 16)
    rng = np.random.default_rng(63)
    n, width = 120, 24
    mat = np.stack([
        np.sort(rng.choice(1 << 10, size=width,
                           replace=False)).astype(np.uint64)
        for _ in range(n)
    ])
    lens = np.full(n, width, dtype=np.int64)
    pi, pj, counts = col.collision_pair_counts(mat, lens)
    got = {(int(a), int(b)): int(c) for a, b, c in zip(pi, pj, counts)}
    assert got == _brute(mat, lens)
    assert _COMPACT_EVERY > 16  # the real threshold is untouched
