"""Blocked fragment-ANI Pallas kernel: parity, selection, packing.

The kernel (ops/pallas_fragment.py) must be undetectable from the
results side: per-element membership flags identical to numpy's
definition over a bucket-boundary lattice, per-window matched counts
bit-identical to the XLA searchsorted and compiled-C merge strategies,
and DirectedANI / cluster compositions byte-for-byte equal under every
GALAH_TPU_FRAGMENT_STRATEGY pin. The packing contract (ONE launch per
pow2-bucketed shape group, pair cap honored) is pinned through the
timing counters the bench stage reads.

All kernel executions here run interpret=True (CPU container); the
hardware suite re-runs the lattice on a real chip via test_tpu_hw.
"""

import logging

import numpy as np
import pytest

from galah_tpu.io.fasta import Genome, GenomeStats
from galah_tpu.ops import fragment_ani as fa
from galah_tpu.ops import pallas_fragment as pf
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.utils import timing

K, FRAGLEN, SUB_C = 15, 500, 2
FLOOR = 0.80
FRAC = fa.DEFAULT_MIN_WINDOW_VALID_FRAC


def _genome(codes, name):
    n = codes.shape[0]
    return Genome(path=f"{name}.fna", codes=codes,
                  contig_offsets=np.array([0, n], dtype=np.int64),
                  stats=GenomeStats(1, int((codes == 255).sum()), n))


def _counter_delta(before, after):
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in set(before) | set(after)
            if after.get(k, 0) != before.get(k, 0)}


# -- kernel-level membership lattice ---------------------------------


def test_kernel_hits_match_numpy_membership_lattice():
    """Per-element flags == np.isin over job/ref pow2 boundaries,
    duplicates, empty sides, all-hit and no-hit extremes — every item
    packed into the SAME window_element_hits call so the multi-pair
    launch path (dedup'd block table, sentinel padding block, superset
    windows) is what gets exercised."""
    rng = np.random.default_rng(11)
    qb = pf.A_SUB * pf.QLA          # 1024: the job quantum
    rb = pf.RSB * pf.B_LANE         # 1024: the ref block quantum

    def u64s(n, hi=1 << 62):
        return np.unique(rng.integers(0, hi, size=n + 64,
                                      dtype=np.uint64))[:n]

    ref_small = np.sort(u64s(1000))
    ref_edge = np.sort(u64s(4 * rb + 1))   # pads 4097 -> 8192 (8 blocks)
    cases = []
    # (qh, ref) lattice: job boundary sizes x ref sets
    for n_q in (1, qb - 1, qb, qb + 1):
        mix = np.concatenate([
            rng.choice(ref_edge, size=max(n_q // 2, 1)),
            u64s(n_q)[:n_q - max(n_q // 2, 1)]])
        cases.append((np.sort(mix[:n_q]), ref_edge))
    cases.append((np.zeros(0, dtype=np.uint64), ref_small))  # empty q
    cases.append((np.sort(u64s(300)),
                  np.zeros(0, dtype=np.uint64)))             # empty ref
    cases.append((np.sort(ref_small[:200]), ref_small))      # all hit
    dup = np.sort(np.repeat(ref_small[:64], 8))              # dup q vals
    cases.append((dup, ref_small))
    cases.append((np.sort(u64s(500) | np.uint64(1 << 63)),
                  ref_small))                                # no hit
    # two items SHARING one padded ref (block-table dedup path)
    shared = fa.pad_ref_set(ref_edge)
    items = [(qh, ref, fa.pad_ref_set(ref)) for qh, ref in cases]
    items.append((np.sort(u64s(700)), ref_edge, shared))
    items.append((np.sort(u64s(900)), ref_edge, shared))

    before = timing.GLOBAL.counters()
    hits = pf.window_element_hits(items, interpret=True)
    delta = _counter_delta(before, timing.GLOBAL.counters())

    for (qh, ref, _rp), h in zip(items, hits):
        expect = np.isin(qh, ref).astype(np.int32)
        np.testing.assert_array_equal(h, expect)
    # every live item packs into one launch (jobs far below the cap);
    # the empty-query item short-circuits without a job slot
    assert delta.get("fragment-pallas-launches") == 1
    assert delta.get("fragment-pallas-pairs") == len(items) - 1


def test_kernel_sentinel_queries_never_match():
    """SENTINEL-valued query slots (the packer's tail padding value)
    are masked even when the reference padding carries the same
    sentinel pattern."""
    ref = np.sort(np.unique(np.random.default_rng(3).integers(
        0, 1 << 62, size=500, dtype=np.uint64)))
    qh = np.concatenate([ref[:10],
                         np.full(5, np.uint64(SENTINEL))])
    qh = np.sort(qh)
    (h,) = pf.window_element_hits(
        [(qh, ref, fa.pad_ref_set(ref))], interpret=True)
    np.testing.assert_array_equal(h, np.isin(qh, ref).astype(np.int32))
    assert int(h.sum()) == 10


# -- profile-level strategy parity -----------------------------------


@pytest.fixture(scope="module")
def profiles():
    """Six profiles spanning the hazard space: near-identical mutants,
    an ambiguous-base run, a repeat-tiled genome, and a larger genome
    that lands in a different pow2 ref bucket."""
    rng = np.random.default_rng(7)
    size = 8_000
    base = rng.integers(0, 4, size=size).astype(np.uint8)
    variants = [("base", base)]
    for rate in (0.01, 0.05):
        v = base.copy()
        mut = rng.random(size) < rate
        v[mut] = rng.integers(0, 4, size=int(mut.sum())).astype(np.uint8)
        variants.append((f"mut{rate}", v))
    amb = base.copy()
    amb[2000:2600] = 255
    variants.append(("ambig", amb))
    seg = rng.integers(0, 4, size=1_000).astype(np.uint8)
    variants.append(("repeat", np.tile(seg, 8)))
    variants.append(("big", rng.integers(0, 4,
                                         size=17_000).astype(np.uint8)))
    return [fa.build_profile(_genome(codes, name), K, FRAGLEN,
                             subsample_c=SUB_C)
            for name, codes in variants]


@pytest.fixture(scope="module")
def pairs(profiles):
    return [(profiles[i], profiles[j])
            for i in range(len(profiles))
            for j in range(len(profiles)) if i != j]


@pytest.fixture(scope="module")
def strategy_results(pairs):
    """Each strategy's DirectedANI list over the same pairs, plus the
    pallas run's launch-counter deltas (the dispatch-count acceptance
    evidence) — computed once for the whole module."""
    before = timing.GLOBAL.counters()
    res = {"pallas": fa._directed_ani_batch_pallas(pairs, FLOOR, FRAC)}
    counters = _counter_delta(before, timing.GLOBAL.counters())
    res["xla"] = fa._directed_ani_batch_xla(pairs, FLOOR, FRAC)
    if fa._c_merge_available():
        res["c"] = fa._directed_ani_batch_cmerge(pairs, FLOOR, FRAC, 1)
    return res, counters


def test_per_window_counts_bit_identical(profiles):
    """The raw per-window matched integers — not just the reduced
    floats — agree across pallas / xla / C for representative pairs,
    including the repeat-tiled and ambiguous-run genomes."""
    sel = [(profiles[0], profiles[1]), (profiles[1], profiles[0]),
           (profiles[4], profiles[0]), (profiles[3], profiles[5]),
           (profiles[5], profiles[3])]
    items = [(q.sorted_query()[0], r.ref_set, r.padded_ref_set())
             for q, r in sel]
    hits = pf.window_element_hits(items, interpret=True)
    for (q, r), h in zip(sel, hits):
        qh, qw, totals = q.sorted_query()
        w = q.n_windows
        pallas_m = np.bincount(qw[h != 0], minlength=w).astype(np.int32)
        xm, xt = fa._window_match_counts(q.device_windows(),
                                         r.device_ref_set())
        np.testing.assert_array_equal(pallas_m, np.asarray(xm)[:w])
        np.testing.assert_array_equal(totals, np.asarray(xt)[:w])
        if fa._c_merge_available():
            from galah_tpu.ops._cpairstats import \
                window_match_counts_merge

            cm = window_match_counts_merge(qh, qw, w, r.ref_set,
                                           validate=False)
            np.testing.assert_array_equal(pallas_m, np.asarray(cm))


def test_directed_ani_bit_identical_across_strategies(strategy_results):
    res, _ = strategy_results
    assert len(res) >= 2
    ref = res["pallas"]
    # parity must not be vacuous: mutant pairs align with high identity
    assert any(d.ani > 0.9 and d.frags_matching > 0 for d in ref)
    for name, got in res.items():
        assert len(got) == len(ref)
        for i, (a, b) in enumerate(zip(ref, got)):
            assert a == b, (name, i, a, b)


def test_one_launch_per_shape_group(pairs, strategy_results):
    """Acceptance: the pallas path dispatches ONE kernel launch per
    pow2-bucketed shape group, not one per pair."""
    _, counters = strategy_results
    groups = {(q.padded_windows().shape, r.padded_ref_set().shape[0],
               q.k, q.fraglen, q.subsample_c) for q, r in pairs}
    assert counters["fragment-pallas-launches"] == len(groups)
    assert len(groups) < len(pairs)
    assert counters["fragment-pallas-pairs"] == len(pairs)
    assert counters["fragment-pallas-jobs"] <= \
        counters["fragment-pallas-job-slots"]
    assert counters["fragment-pallas-ref-blocks-needed"] <= \
        counters["fragment-pallas-ref-blocks"]


def test_pair_cap_splits_launches(pairs, monkeypatch):
    """GALAH_TPU_FRAGMENT_PAIRS=1 degenerates packing to one launch
    per pair — and the results stay identical."""
    sub = pairs[:3]
    monkeypatch.setenv("GALAH_TPU_FRAGMENT_PAIRS", "1")
    before = timing.GLOBAL.counters()
    capped = fa._directed_ani_batch_pallas(sub, FLOOR, FRAC)
    delta = _counter_delta(before, timing.GLOBAL.counters())
    assert delta["fragment-pallas-launches"] == len(sub)
    monkeypatch.delenv("GALAH_TPU_FRAGMENT_PAIRS")
    assert capped == fa._directed_ani_batch_pallas(sub, FLOOR, FRAC)


def test_zero_window_query_parity(profiles):
    """A shorter-than-k genome (zero windows, empty query) flows
    through the pallas path's short-circuit and matches XLA."""
    tiny = fa.build_profile(
        _genome(np.array([0, 1, 2, 3] * 2, dtype=np.uint8), "tiny"),
        K, FRAGLEN, subsample_c=SUB_C)
    batch = [(tiny, profiles[0]), (profiles[0], tiny),
             (profiles[0], profiles[1])]
    got = fa._directed_ani_batch_pallas(batch, FLOOR, FRAC)
    assert got[0] == fa.DirectedANI(0.0, 0.0, 0, 0)
    assert got == fa._directed_ani_batch_xla(batch, FLOOR, FRAC)


def test_bidirectional_values_parity_under_env_pins(pairs, monkeypatch):
    """The public bidirectional entry point returns identical gated
    values under every strategy pin."""
    sub = pairs[:4]
    outs = {}
    for s in ("pallas", "xla") + (("c",)
                                  if fa._c_merge_available() else ()):
        monkeypatch.setenv("GALAH_TPU_FRAGMENT_STRATEGY", s)
        outs[s] = fa.bidirectional_ani_values(sub, 0.15)
    assert all(v == outs["pallas"] for v in outs.values())
    assert any(v is not None for v in outs["pallas"])


# -- strategy resolution ---------------------------------------------


def test_auto_selection_heuristic(monkeypatch):
    monkeypatch.delenv("GALAH_TPU_FRAGMENT_STRATEGY", raising=False)
    r = fa._resolve_fragment_strategy
    assert r(backend="cpu", n_devices=1, c_ok=True) == ("c", False)
    assert r(backend="cpu", n_devices=1, c_ok=False) == ("xla", False)
    # multi-device CPU mesh: the sharded XLA batch path wins
    assert r(backend="cpu", n_devices=8, c_ok=True) == ("xla", False)
    monkeypatch.setattr("galah_tpu.ops.hll.use_pallas_default",
                        lambda: True)
    assert r(backend="tpu", n_devices=4, c_ok=True) == ("pallas", False)
    monkeypatch.setattr("galah_tpu.ops.hll.use_pallas_default",
                        lambda: False)
    assert r(backend="tpu", n_devices=4, c_ok=True) == ("xla", False)


def test_env_pin_beats_auto(monkeypatch):
    for s in fa.FRAGMENT_STRATEGIES:
        monkeypatch.setenv("GALAH_TPU_FRAGMENT_STRATEGY", s)
        # the pin wins over every injected runtime shape
        assert fa._resolve_fragment_strategy(
            backend="cpu", n_devices=1, c_ok=True) == (s, True)
    monkeypatch.setenv("GALAH_TPU_FRAGMENT_STRATEGY", "")
    assert fa._resolve_fragment_strategy(
        backend="cpu", n_devices=1, c_ok=True) == ("c", False)


def test_strategy_counter_records_resolution(pairs, monkeypatch):
    monkeypatch.setenv("GALAH_TPU_FRAGMENT_STRATEGY", "xla")
    before = timing.GLOBAL.counters()
    fa.directed_ani_batch(pairs[:2], FLOOR, FRAC)
    delta = _counter_delta(before, timing.GLOBAL.counters())
    assert delta.get("fragment-strategy-xla") == 1


# -- fallback / demotion policy --------------------------------------


def _broken_kernel(*_a, **_k):
    raise RuntimeError("forced fragment kernel failure")


def test_auto_pallas_failure_demotes_to_xla(pairs, monkeypatch, caplog):
    """AUTO-chosen pallas that fails at runtime demotes to the XLA
    twin (identical results), counts the demotion, and warns — it must
    never take down a production run."""
    sub = pairs[:3]
    monkeypatch.delenv("GALAH_TPU_FRAGMENT_STRATEGY", raising=False)
    monkeypatch.setattr(fa, "_resolve_fragment_strategy",
                        lambda *a, **k: ("pallas", False))
    monkeypatch.setattr(pf, "window_element_hits", _broken_kernel)
    before = timing.GLOBAL.counters()
    with caplog.at_level(logging.WARNING, logger="galah_tpu.ops._fallback"):
        got = fa.directed_ani_batch(sub, FLOOR, FRAC)
    delta = _counter_delta(before, timing.GLOBAL.counters())
    assert got == fa._directed_ani_batch_xla(sub, FLOOR, FRAC)
    assert delta.get("fragment-pallas-demoted") == 1
    assert any("fragment window-match kernel" in r.getMessage()
               for r in caplog.records)


def test_explicit_pin_propagates_kernel_failure(pairs, monkeypatch):
    """A pinned pallas run must fail loudly — parity captures must
    never silently compare the fallback to itself."""
    monkeypatch.setenv("GALAH_TPU_FRAGMENT_STRATEGY", "pallas")
    monkeypatch.setattr(pf, "window_element_hits", _broken_kernel)
    with pytest.raises(RuntimeError, match="forced fragment"):
        fa.directed_ani_batch(pairs[:2], FLOOR, FRAC)


# -- end-to-end cluster-composition parity ---------------------------


def _write_family(tmp_path):
    rng = np.random.default_rng(23)
    base = rng.integers(0, 4, size=20_000)
    seqs = [base]
    mut = base.copy()
    sites = rng.random(mut.shape[0]) < 0.01
    mut[sites] = (mut[sites]
                  + rng.integers(1, 4, size=int(sites.sum()))) % 4
    seqs.append(mut)
    seqs.append(rng.integers(0, 4, size=20_000))  # unrelated
    paths = []
    for i, s in enumerate(seqs):
        p = tmp_path / f"g{i}.fna"
        p.write_text(">c\n" + "".join("ACGT"[c] for c in s) + "\n")
        paths.append(str(p))
    return paths


def test_cluster_composition_parity_across_strategies(tmp_path,
                                                      monkeypatch):
    """Full pipeline under each strategy pin produces the same
    clusters: the 1%-mutant joins its base, the unrelated genome
    stays a singleton."""
    from galah_tpu.api import generate_galah_clusterer

    paths = _write_family(tmp_path)
    values = {"ani": 95.0, "precluster_ani": 90.0,
              "min_aligned_fraction": 15.0, "fragment_length": 3000,
              "precluster_method": "skani", "cluster_method": "skani",
              "threads": 1}
    strategies = ["pallas", "xla"]
    if fa._c_merge_available():
        strategies.append("c")
    outs = {}
    for s in strategies:
        monkeypatch.setenv("GALAH_TPU_FRAGMENT_STRATEGY", s)
        clusters = generate_galah_clusterer(paths, values).cluster()
        outs[s] = sorted(sorted(c) for c in clusters)
    assert outs["pallas"] == [[0, 1], [2]]
    assert all(v == outs["pallas"] for v in outs.values())


@pytest.mark.parametrize("strategy", ["pallas", "xla"])
def test_abisko_golden_clusters_per_strategy(ref_data, monkeypatch,
                                             strategy):
    """Reference-data golden (reference: src/clusterer.rs:481-533 pins
    [[0,1,3],[2]] at 98): the campaign clustering is invariant under
    the membership strategy pin."""
    from galah_tpu.api import generate_galah_clusterer

    names = ["abisko4/73.20120800_S1X.13.fna",
             "abisko4/73.20120600_S2D.19.fna",
             "abisko4/73.20120700_S3X.12.fna",
             "abisko4/73.20110800_S2D.13.fna"]
    monkeypatch.setenv("GALAH_TPU_FRAGMENT_STRATEGY", strategy)
    values = {"ani": 98.0, "precluster_ani": 90.0,
              "min_aligned_fraction": 20.0, "fragment_length": 3000,
              "precluster_method": "skani", "cluster_method": "skani",
              "threads": 1}
    clusterer = generate_galah_clusterer(
        [str(ref_data / n) for n in names], values)
    assert sorted(sorted(c) for c in clusterer.cluster()) == \
        [[0, 1, 3], [2]]
