"""Clustering-engine semantics, pinned with deterministic stub backends.

These tests encode the reference's engine behaviors (reference:
src/clusterer.rs) without real sketching: quality-ordered greedy rep
selection, precluster partitioning, ANI-reuse when methods match,
membership argmax (including its no-threshold-filter quirk), and cache
carry-over between phases.
"""

from typing import List, Optional, Sequence

from galah_tpu.backends.base import ClusterBackend, PreclusterBackend
from galah_tpu.cluster import cluster
from galah_tpu.cluster.cache import PairDistanceCache, pair_key
from galah_tpu.cluster.partition import partition_preclusters


class StubPreclusterer(PreclusterBackend):
    def __init__(self, pairs, name="stub"):
        self.pairs = pairs
        self.name = name

    def method_name(self):
        return self.name

    def distances(self, genome_paths):
        cache = PairDistanceCache()
        for (i, j), ani in self.pairs.items():
            cache.insert((i, j), ani)
        return cache


class StubClusterer(ClusterBackend):
    """Exact ANI from a lookup table keyed by basename pairs."""

    def __init__(self, table, threshold, name="stub-exact"):
        self.table = {frozenset(k): v for k, v in table.items()}
        self.threshold = threshold
        self.name = name
        self.calls: List[tuple] = []

    def method_name(self):
        return self.name

    @property
    def ani_threshold(self):
        return self.threshold

    def calculate_ani_batch(self, pairs: Sequence[tuple]) -> List[Optional[float]]:
        self.calls.append(list(pairs))
        return [self.table.get(frozenset(p)) for p in pairs]


def g(n):
    return [f"g{i}.fna" for i in range(n)]


def test_partition_single_linkage():
    # chain 0-1, 1-2 links a component of 3; 3 is a singleton
    comps = partition_preclusters(4, [(0, 1), (1, 2)])
    assert comps == [[0, 1, 2], [3]]


def test_partition_biggest_first():
    comps = partition_preclusters(5, [(3, 4)])
    assert comps[0] == [3, 4]
    assert [len(c) for c in comps] == [2, 1, 1, 1]


def test_greedy_quality_order_reps():
    """Genome 0 (best quality) becomes rep; 1 joins it; 2 is its own rep."""
    pre = StubPreclusterer({(0, 1): 0.97, (0, 2): 0.91})
    cl = StubClusterer(
        {("g0.fna", "g1.fna"): 0.96, ("g0.fna", "g2.fna"): 0.90},
        threshold=0.95)
    out = cluster(g(3), pre, cl)
    assert out == [[0, 1], [2]]


def test_rep_decision_requires_threshold():
    """Candidate ANI below threshold leaves the genome as its own rep."""
    pre = StubPreclusterer({(0, 1): 0.99})
    cl = StubClusterer({("g0.fna", "g1.fna"): 0.90}, threshold=0.95)
    assert cluster(g(2), pre, cl) == [[0], [1]]


def test_no_precluster_hit_means_no_ani_call():
    """Pairs without a precluster hit are never sent to the backend."""
    pre = StubPreclusterer({(0, 1): 0.96})
    cl = StubClusterer({("g0.fna", "g1.fna"): 0.96,
                        ("g0.fna", "g2.fna"): 0.99}, threshold=0.95)
    out = cluster(g(3), pre, cl)
    assert out == [[0, 1], [2]]
    flat = [frozenset(p) for batch in cl.calls for p in batch]
    assert frozenset(("g0.fna", "g2.fna")) not in flat


def test_membership_argmax_over_reps():
    """Non-rep joins the rep with the HIGHEST exact ANI, not the first."""
    # 0 and 1 both reps (ANI between them below threshold); 2 passes
    # threshold to both but is closer to 1.
    pre = StubPreclusterer({(0, 1): 0.92, (0, 2): 0.97, (1, 2): 0.98})
    cl = StubClusterer({
        ("g0.fna", "g1.fna"): 0.90,
        ("g0.fna", "g2.fna"): 0.96,
        ("g1.fna", "g2.fna"): 0.97,
    }, threshold=0.95)
    assert cluster(g(3), pre, cl) == [[0], [1, 2]]


def test_membership_argmax_ignores_threshold():
    """Quirk preserved from the reference (src/clusterer.rs:371-403):
    membership argmax considers sub-threshold cached ANIs too. Genome 2
    fails the rep test against rep 0 (ANI 0.96 >= thr), but its best
    cached ANI is to rep 1 at 0.94 < threshold — it still joins rep 1."""
    pre = StubPreclusterer({(0, 2): 0.97, (1, 2): 0.99})
    cl = StubClusterer({
        ("g0.fna", "g2.fna"): 0.96,
        ("g1.fna", "g2.fna"): 0.94,  # computed in rep phase, cached
    }, threshold=0.95)
    out = cluster(g(3), pre, cl)
    # reps: 0, then 1 (no precluster hit 0-1); 2: candidates {0, 1} ->
    # ANIs 0.96 (>=thr, not rep) and 0.94; argmax = 0.96 -> joins 0?
    # No: argmax over cached = max(0.96, 0.94) = 0.96 -> rep 0. But if
    # both cached, highest wins regardless of threshold.
    assert out == [[0, 2], [1]]


def test_membership_subthreshold_best_wins():
    """If the only ANI >= threshold is 0.96 to rep 0 but rep 1 has a
    cached 0.97 (also computed in rep phase), the 0.97 rep wins."""
    pre = StubPreclusterer({(0, 2): 0.97, (1, 2): 0.99, (0, 1): 0.90})
    cl = StubClusterer({
        ("g0.fna", "g1.fna"): 0.80,   # 1 still becomes its own rep
        ("g0.fna", "g2.fna"): 0.96,
        ("g1.fna", "g2.fna"): 0.97,
    }, threshold=0.95)
    assert cluster(g(3), pre, cl) == [[0], [1, 2]]


def test_ani_reuse_when_methods_match():
    """skip_clusterer: same method name -> no exact-ANI calls at all."""
    pre = StubPreclusterer({(0, 1): 0.97}, name="same")
    cl = StubClusterer({}, threshold=0.95, name="same")
    out = cluster(g(2), pre, cl)
    assert out == [[0, 1]]
    assert cl.calls == [] or all(len(b) == 0 for b in cl.calls)


def test_none_ani_not_a_match():
    """None (failed aligned-fraction gate) never counts as >= threshold."""
    pre = StubPreclusterer({(0, 1): 0.99})
    cl = StubClusterer({}, threshold=0.95)  # lookup miss -> None
    assert cluster(g(2), pre, cl) == [[0], [1]]


def test_preclusters_isolate_ani_calls():
    """Genomes in different preclusters are never compared."""
    pre = StubPreclusterer({(0, 1): 0.97, (2, 3): 0.97})
    cl = StubClusterer({
        ("g0.fna", "g1.fna"): 0.96,
        ("g2.fna", "g3.fna"): 0.96,
    }, threshold=0.95)
    out = cluster(g(4), pre, cl)
    assert out == [[0, 1], [2, 3]]
    flat = [frozenset(p) for batch in cl.calls for p in batch]
    assert frozenset(("g0.fna", "g2.fna")) not in flat


def test_cache_transform_ids():
    cache = PairDistanceCache()
    cache.insert((2, 5), 0.9)
    cache.insert((5, 7), 0.8)
    cache.insert((1, 9), 0.7)
    local = cache.transform_ids([2, 5, 7])
    assert local.get((0, 1)) == 0.9
    assert local.get((1, 2)) == 0.8
    assert len(local) == 2


def test_pair_key_sorted():
    assert pair_key(5, 2) == (2, 5)
    assert pair_key(2, 5) == (2, 5)


def test_windowed_rep_scan_bounds_dispatches(monkeypatch):
    """A large precluster (above the dense-warm cap) must issue far
    fewer backend batches than one per genome: the windowed rep scan
    (engine.REP_SCAN_WINDOW) batches a window of upcoming genomes
    against all current reps speculatively."""
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "host")
    n = 200
    # one family: genome 0 absorbs everyone (ANI 0.99 to all); all
    # pairs are precluster hits so the candidate sets are maximal
    pre_pairs = {(i, j): 0.97 for i in range(n) for j in range(i + 1, n)}
    table = {}
    for i in range(n):
        for j in range(i + 1, n):
            # chain to rep 0 only: others stay below threshold
            table[(f"g{i}.fna", f"g{j}.fna")] = 0.99 if i == 0 else 0.80
    pre = StubPreclusterer(pre_pairs, name="pre")
    cl = StubClusterer(table, threshold=0.95, name="exact")
    clusters = cluster(g(n), pre, cl, dense_precluster_cap=0)
    assert sorted(len(c) for c in clusters)[-1] == n  # one big cluster
    # one speculative batch per 128-genome window (2 windows at n=200),
    # plus one batch per genome that saw a rep emerge inside its window
    # (only genome 1: rep 0 emerges in window 0 before it). Allow a
    # little slack but pin "far fewer than n".
    assert len(cl.calls) <= 8, len(cl.calls)


def test_rep_scan_window_invariance_and_waste_counters(monkeypatch):
    """Clusters are identical for any rep_scan_window (the speculative
    batches only pre-fill the ANI cache; decisions read the same
    values), and the waste counters account computed vs consulted."""
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "host")
    from galah_tpu.utils import timing

    n = 60
    rng_pairs = {(i, j): 0.96 for i in range(n) for j in range(i + 1, n)}
    table = {}
    for i in range(n):
        for j in range(i + 1, n):
            fam_i, fam_j = i % 3, j % 3
            table[(f"g{i}.fna", f"g{j}.fna")] = (
                0.99 if fam_i == fam_j else 0.80)
    pre = StubPreclusterer(rng_pairs, name="pre")

    results = []
    for window in (None, 1, 7):
        cl = StubClusterer(table, threshold=0.95, name="exact")
        before = timing.GLOBAL.counters()
        clusters = cluster(g(n), pre, cl, dense_precluster_cap=0,
                           rep_scan_window=window)
        after = timing.GLOBAL.counters()
        results.append(sorted(sorted(c) for c in clusters))
        computed = (after.get("exact-ani-computed", 0)
                    - before.get("exact-ani-computed", 0))
        wasted = (after.get("exact-ani-wasted", 0)
                  - before.get("exact-ani-wasted", 0))
        assert computed > 0
        assert 0 <= wasted <= computed
    assert results[0] == results[1] == results[2]
    # 3 families of 20
    assert [len(c) for c in results[0]] == [20, 20, 20]


def test_warm_pass_waste_is_counted(monkeypatch):
    """The dense-warm path's upfront ANIs enter the computed counter,
    so unconsulted warm pairs surface as waste (the warm pass belongs
    to the host strategy; the device rounds never over-materialize)."""
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "host")
    from galah_tpu.utils import timing

    n = 8
    pre_pairs = {(i, j): 0.96 for i in range(n) for j in range(i + 1, n)}
    table = {(f"g{i}.fna", f"g{j}.fna"): 0.99
             for i in range(n) for j in range(i + 1, n)}
    pre = StubPreclusterer(pre_pairs, name="pre")
    cl = StubClusterer(table, threshold=0.95, name="exact")
    before = timing.GLOBAL.counters()
    clusters = cluster(g(n), pre, cl)  # default dense cap: warm path
    after = timing.GLOBAL.counters()
    assert len(clusters) == 1
    computed = (after.get("exact-ani-computed", 0)
                - before.get("exact-ani-computed", 0))
    # every hit pair was warmed upfront: n*(n-1)/2
    assert computed == n * (n - 1) // 2


def test_transform_ids_probe_and_scan_branches_agree():
    """transform_ids picks probe-vs-scan by size; both must agree,
    including stored-None values and duplicate-free remapping."""
    import numpy as np

    from galah_tpu.cluster.cache import PairDistanceCache

    rng = np.random.default_rng(81)
    cache = PairDistanceCache()
    for _ in range(300):
        i, j = map(int, rng.integers(0, 60, size=2))
        if i == j:
            continue
        v = None if rng.random() < 0.2 else float(rng.random())
        cache.insert((i, j), v)
    # m=2/4/9 take the probe branch (m^2/2 < cache size), m=40 takes
    # the scan branch (780 candidate pairs > ~260 cached); the oracle
    # below is branch-independent (contains/get per candidate pair),
    # so both branches are checked against the same contract.
    for m in (2, 4, 9, 40):
        indices = sorted(map(int, rng.choice(60, size=m, replace=False)))
        got = cache.transform_ids(indices)
        want = PairDistanceCache()
        for a in range(m):
            for b in range(a + 1, m):
                if cache.contains((indices[a], indices[b])):
                    want.insert((a, b),
                                cache.get((indices[a], indices[b])))
        assert got == want
