"""Mosaic pairlist kernel: bit-parity with the XLA pair stats, in
interpreter mode on the CPU test mesh (hardware lowering is covered by
tests/test_tpu_hw.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pairwise import _pair_stats
from galah_tpu.ops.pallas_pairlist import pair_stats_pairs_pallas


def _rand_sketches(rng, n, width):
    mat = np.full((n, width), np.uint64(SENTINEL), dtype=np.uint64)
    for i in range(n):
        cut = int(rng.integers(1, width + 1))
        vals = rng.choice(1 << 62, size=cut, replace=False)
        mat[i, :cut] = np.sort(vals.astype(np.uint64))
    return mat


def _xla_pairs(a, b, sketch_size):
    c, t = jax.vmap(
        lambda x, y: _pair_stats(x, y, sketch_size)
    )(jnp.asarray(a), jnp.asarray(b))
    return np.asarray(c), np.asarray(t)


# Interpret-mode tracing of this kernel is expensive (K_pad=1024 =>
# 128 unrolled lane columns; the range_skip variant adds 1024 pl.when
# branches), so the full parity matrix lives in the slow tier; the
# default tier keeps edge_rows (both variants) + one random-matrix
# case as the per-commit smoke parity.
@pytest.mark.parametrize("range_skip", [
    False, pytest.param(True, marks=pytest.mark.slow)])
@pytest.mark.parametrize("n_pairs,width", [
    (130, 256), pytest.param(64, 1024, marks=pytest.mark.slow)])
def test_pairlist_matches_xla(n_pairs, width, range_skip):
    rng = np.random.default_rng(n_pairs)
    mat = _rand_sketches(rng, 80, width)
    # overlapping families so commons are non-trivial
    for i in range(0, 80, 4):
        mat[i + 1, : width // 2] = mat[i, : width // 2]
        mat[i + 1].sort()
    pi = rng.integers(0, 80, size=n_pairs)
    pj = rng.integers(0, 80, size=n_pairs)
    a, b = mat[pi], mat[pj]
    want_c, want_t = _xla_pairs(a, b, width)
    got_c, got_t = pair_stats_pairs_pallas(
        jnp.asarray(a), jnp.asarray(b), width, interpret=True,
        range_skip=range_skip)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)
    np.testing.assert_array_equal(np.asarray(got_t), want_t)


@pytest.mark.parametrize("range_skip", [
    False,
    # the skip variant costs ~3x in interpret mode and its default is
    # decided (OFF, 2026-08-01 hardware data) — slow tier keeps the
    # coverage without taxing the default loop
    pytest.param(True, marks=pytest.mark.slow),
])
def test_pairlist_edge_rows(range_skip):
    """Empty rows, identical rows, all-sentinel pads, tiny batch."""
    rng = np.random.default_rng(3)
    width = 128
    mat = _rand_sketches(rng, 8, width)
    mat[2] = np.uint64(SENTINEL)            # empty
    mat[5] = mat[4]                         # identical pair
    pi = np.array([0, 2, 4, 5, 2])
    pj = np.array([1, 3, 5, 5, 2])
    a, b = mat[pi], mat[pj]
    want_c, want_t = _xla_pairs(a, b, width)
    got_c, got_t = pair_stats_pairs_pallas(
        jnp.asarray(a), jnp.asarray(b), width, interpret=True,
        range_skip=range_skip)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)
    np.testing.assert_array_equal(np.asarray(got_t), want_t)


@pytest.mark.slow
def test_pairlist_respects_sketch_size_cap():
    """sketch_size below the row width caps `total` identically."""
    rng = np.random.default_rng(11)
    width = 256
    mat = _rand_sketches(rng, 16, width)
    pi = rng.integers(0, 16, size=40)
    pj = rng.integers(0, 16, size=40)
    a, b = mat[pi], mat[pj]
    want_c, want_t = _xla_pairs(a, b, 100)
    got_c, got_t = pair_stats_pairs_pallas(
        jnp.asarray(a), jnp.asarray(b), 100, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)
    np.testing.assert_array_equal(np.asarray(got_t), want_t)


def test_wired_sparse_batch_path_interpret():
    """The production wiring (pair_stats_for_pairs with the pallas
    route, batch pad/trim included) matches the XLA route — interpret
    mode stands in for Mosaic on the CPU mesh."""
    from galah_tpu.ops.sparse_device import pair_stats_for_pairs

    rng = np.random.default_rng(21)
    mat = _rand_sketches(rng, 60, 256)
    pi = rng.integers(0, 60, size=333)
    pj = rng.integers(0, 60, size=333)
    c_xla, t_xla = pair_stats_for_pairs(mat, pi, pj, 256,
                                        use_pallas=False)
    c_pl, t_pl = pair_stats_for_pairs(mat, pi, pj, 256,
                                      use_pallas=True, interpret=True,
                                      batch=128)
    np.testing.assert_array_equal(c_pl, c_xla)
    np.testing.assert_array_equal(t_pl, t_xla)
