"""Mosaic pairlist kernel: bit-parity with the XLA pair stats, in
interpreter mode on the CPU test mesh (hardware lowering is covered by
tests/test_tpu_hw.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pairwise import _pair_stats
from galah_tpu.ops.pallas_pairlist import pair_stats_pairs_pallas


def _rand_sketches(rng, n, width):
    mat = np.full((n, width), np.uint64(SENTINEL), dtype=np.uint64)
    for i in range(n):
        cut = int(rng.integers(1, width + 1))
        vals = rng.choice(1 << 62, size=cut, replace=False)
        mat[i, :cut] = np.sort(vals.astype(np.uint64))
    return mat


def _xla_pairs(a, b, sketch_size):
    c, t = jax.vmap(
        lambda x, y: _pair_stats(x, y, sketch_size)
    )(jnp.asarray(a), jnp.asarray(b))
    return np.asarray(c), np.asarray(t)


# Interpret-mode tracing of this kernel is expensive (K_pad=1024 =>
# 128 unrolled lane columns; the range_skip variant adds 1024 pl.when
# branches), so the full parity matrix lives in the slow tier; the
# default tier keeps edge_rows (both variants) + one random-matrix
# case as the per-commit smoke parity.
@pytest.mark.parametrize("range_skip", [
    False, pytest.param(True, marks=pytest.mark.slow)])
@pytest.mark.slow
@pytest.mark.parametrize("n_pairs,width", [
    (21, 256), (130, 256), (64, 1024)])
def test_pairlist_matches_xla(n_pairs, width, range_skip):
    """Random-list parity across widths. Slow tier: each (shape,
    width) pays a ~5 s interpret-mode trace regardless of pair count;
    tier-1 parity for this kernel lives in test_pairlist_edge_rows and
    test_blocked_pair_axis_boundaries (width 128, shared traces)."""
    rng = np.random.default_rng(n_pairs)
    mat = _rand_sketches(rng, 80, width)
    # overlapping families so commons are non-trivial
    for i in range(0, 80, 4):
        mat[i + 1, : width // 2] = mat[i, : width // 2]
        mat[i + 1].sort()
    pi = rng.integers(0, 80, size=n_pairs)
    pj = rng.integers(0, 80, size=n_pairs)
    a, b = mat[pi], mat[pj]
    want_c, want_t = _xla_pairs(a, b, width)
    got_c, got_t = pair_stats_pairs_pallas(
        jnp.asarray(a), jnp.asarray(b), width, interpret=True,
        range_skip=range_skip)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)
    np.testing.assert_array_equal(np.asarray(got_t), want_t)


@pytest.mark.parametrize("range_skip", [
    False,
    # the skip variant costs ~3x in interpret mode and its default is
    # decided (OFF, 2026-08-01 hardware data) — slow tier keeps the
    # coverage without taxing the default loop
    pytest.param(True, marks=pytest.mark.slow),
])
def test_pairlist_edge_rows(range_skip):
    """Empty rows, identical rows, all-sentinel pads, tiny batch."""
    rng = np.random.default_rng(3)
    width = 128
    mat = _rand_sketches(rng, 8, width)
    mat[2] = np.uint64(SENTINEL)            # empty
    mat[5] = mat[4]                         # identical pair
    pi = np.array([0, 2, 4, 5, 2])
    pj = np.array([1, 3, 5, 5, 2])
    a, b = mat[pi], mat[pj]
    want_c, want_t = _xla_pairs(a, b, width)
    got_c, got_t = pair_stats_pairs_pallas(
        jnp.asarray(a), jnp.asarray(b), width, interpret=True,
        range_skip=range_skip)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)
    np.testing.assert_array_equal(np.asarray(got_t), want_t)


@pytest.mark.slow
def test_pairlist_respects_sketch_size_cap():
    """sketch_size below the row width caps `total` identically."""
    rng = np.random.default_rng(11)
    width = 256
    mat = _rand_sketches(rng, 16, width)
    pi = rng.integers(0, 16, size=40)
    pj = rng.integers(0, 16, size=40)
    a, b = mat[pi], mat[pj]
    want_c, want_t = _xla_pairs(a, b, 100)
    got_c, got_t = pair_stats_pairs_pallas(
        jnp.asarray(a), jnp.asarray(b), 100, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)
    np.testing.assert_array_equal(np.asarray(got_t), want_t)


@pytest.mark.parametrize("n_pairs", [7, 8, 9])
def test_blocked_pair_axis_boundaries(n_pairs):
    """P-1 / P / P+1 pairs at the default block (P=8): the pair-axis
    sentinel padding must fill partial blocks without leaking into
    real outputs, and a full block plus one must spill into a second
    grid step correctly."""
    rng = np.random.default_rng(40 + n_pairs)
    width = 128
    mat = _rand_sketches(rng, 12, width)
    pi = rng.integers(0, 12, size=n_pairs)
    pj = rng.integers(0, 12, size=n_pairs)
    a, b = mat[pi], mat[pj]
    want_c, want_t = _xla_pairs(a, b, width)
    got_c, got_t = pair_stats_pairs_pallas(
        jnp.asarray(a), jnp.asarray(b), width, interpret=True,
        block_pairs=8)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)
    np.testing.assert_array_equal(np.asarray(got_t), want_t)


# Default tier already covers the production P=8 blocked kernel
# (boundaries above + the random-matrix case); the cross-P sweep is
# tracing-bound in interpret mode, so it rides the slow tier.
@pytest.mark.slow
@pytest.mark.parametrize("block_pairs", [1, 2, 4, 8])
def test_blocked_matches_xla_across_block_sizes(block_pairs):
    """Every supported P yields the same integers (P=1 is the retired
    round-5 one-pair grid; a ragged 13-pair list is partial for every
    P here)."""
    rng = np.random.default_rng(50 + block_pairs)
    width = 256
    mat = _rand_sketches(rng, 20, width)
    mat[4] = np.uint64(SENTINEL)            # empty row in the list
    pi = rng.integers(0, 20, size=13)
    pj = rng.integers(0, 20, size=13)
    a, b = mat[pi], mat[pj]
    want_c, want_t = _xla_pairs(a, b, width)
    got_c, got_t = pair_stats_pairs_pallas(
        jnp.asarray(a), jnp.asarray(b), width, interpret=True,
        block_pairs=block_pairs)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)
    np.testing.assert_array_equal(np.asarray(got_t), want_t)


def test_block_env_knob(monkeypatch):
    """GALAH_TPU_PAIRLIST_BLOCK tunes P; it is resolved OUTSIDE the jit
    cache so a change takes effect on the next call."""
    from galah_tpu.ops.pallas_pairlist import (
        PAIRLIST_BLOCK_DEFAULT,
        pairlist_block_pairs,
    )

    monkeypatch.delenv("GALAH_TPU_PAIRLIST_BLOCK", raising=False)
    assert pairlist_block_pairs() == PAIRLIST_BLOCK_DEFAULT
    monkeypatch.setenv("GALAH_TPU_PAIRLIST_BLOCK", "4")
    assert pairlist_block_pairs() == 4
    monkeypatch.setenv("GALAH_TPU_PAIRLIST_BLOCK", "0")
    assert pairlist_block_pairs() == 1


def test_wired_sparse_batch_path_interpret():
    """The production wiring (pair_stats_for_pairs with the pallas
    route, batch pad/trim included) matches the XLA route — interpret
    mode stands in for Mosaic on the CPU mesh."""
    from galah_tpu.ops.sparse_device import pair_stats_for_pairs

    rng = np.random.default_rng(21)
    # width 128 (one lane quantum) keeps the interpret-mode trace
    # cheap; 56 pairs / batch 48 gives two batches, the second ragged,
    # covering the pad/trim seam
    mat = _rand_sketches(rng, 60, 128)
    pi = rng.integers(0, 60, size=56)
    pj = rng.integers(0, 60, size=56)
    c_xla, t_xla = pair_stats_for_pairs(mat, pi, pj, 128,
                                        use_pallas=False)
    c_pl, t_pl = pair_stats_for_pairs(mat, pi, pj, 128,
                                      use_pallas=True, interpret=True,
                                      batch=48)
    np.testing.assert_array_equal(c_pl, c_xla)
    np.testing.assert_array_equal(t_pl, t_xla)
