"""End-to-end golden clusterings on real MAGs.

These reproduce the reference's engine tests (reference:
src/clusterer.rs:481-663): the same four abisko4 MAGs must produce the
same cluster compositions across backend combinations and thresholds.
Clusters are compared as sorted member sets (the reference sorts each
cluster before asserting, and its cluster ordering is thread-timing
dependent; ours is deterministic by representative index).
"""

import pytest

from galah_tpu.backends import (
    FastANIEquivalentClusterer,
    MinHashPreclusterer,
    ProfileStore,
    SkaniEquivalentClusterer,
    SkaniPreclusterer,
)
from galah_tpu.cluster import cluster

ABISKO = [
    "abisko4/73.20120800_S1X.13.fna",
    "abisko4/73.20120600_S2D.19.fna",
    "abisko4/73.20120700_S3X.12.fna",
    "abisko4/73.20110800_S2D.13.fna",
]


def _paths(ref_data, names):
    return [str(ref_data / n) for n in names]


def _sorted_clusters(clusters):
    return sorted(sorted(c) for c in clusters)


@pytest.fixture(scope="module")
def profile_store():
    """One profile store shared across golden tests (profile once)."""
    return ProfileStore(k=15)


def test_minhash_fastani_hello_world(ref_data, profile_store):
    out = cluster(
        _paths(ref_data, ABISKO),
        MinHashPreclusterer(min_ani=0.9),
        FastANIEquivalentClusterer(
            threshold=0.95, min_aligned_fraction=0.2, store=profile_store),
    )
    assert _sorted_clusters(out) == [[0, 1, 2, 3]]


def test_minhash_fastani_two_clusters_same_ani(ref_data, profile_store):
    out = cluster(
        _paths(ref_data, ABISKO),
        MinHashPreclusterer(min_ani=0.9),
        FastANIEquivalentClusterer(
            threshold=0.98, min_aligned_fraction=0.2, store=profile_store),
    )
    assert _sorted_clusters(out) == [[0, 1, 3], [2]]


def test_minhash_skani_hello_world(ref_data, profile_store):
    out = cluster(
        _paths(ref_data, ABISKO),
        MinHashPreclusterer(min_ani=0.9),
        SkaniEquivalentClusterer(
            threshold=0.95, min_aligned_fraction=0.2, store=profile_store),
    )
    assert _sorted_clusters(out) == [[0, 1, 2, 3]]


def test_minhash_skani_two_clusters_same_ani(ref_data, profile_store):
    out = cluster(
        _paths(ref_data, ABISKO),
        MinHashPreclusterer(min_ani=0.9),
        SkaniEquivalentClusterer(
            threshold=0.99, min_aligned_fraction=0.2, store=profile_store),
    )
    assert _sorted_clusters(out) == [[0, 1, 3], [2]]


@pytest.mark.slow
def test_skani_skani_two_clusters_same_ani(ref_data, profile_store):
    out = cluster(
        _paths(ref_data, ABISKO),
        SkaniPreclusterer(
            threshold=0.90, min_aligned_fraction=0.2, store=profile_store),
        SkaniEquivalentClusterer(
            threshold=0.99, min_aligned_fraction=0.2, store=profile_store),
    )
    assert _sorted_clusters(out) == [[0, 1, 3], [2]]


@pytest.mark.slow
def test_skani_skani_two_preclusters(ref_data, profile_store):
    out = cluster(
        _paths(ref_data, ABISKO + ["antonio_mags/BE_RX_R2_MAG52.fna"]),
        SkaniPreclusterer(
            threshold=0.90, min_aligned_fraction=0.2, store=profile_store),
        SkaniEquivalentClusterer(
            threshold=0.99, min_aligned_fraction=0.2, store=profile_store),
    )
    assert _sorted_clusters(out) == [[0, 1, 3], [2], [4]]
