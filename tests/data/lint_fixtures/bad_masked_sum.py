"""GL901 fixture: the PR 5 masked-sum regression class.

``np.where(mask, x, 0)`` keeps the full run length, so a reduceat /
pairwise summation over it groups DIFFERENT blocks than the compressed
segment would — the float drifts a ulp and cross-strategy bit-identity
breaks. Compress first: ``x[mask]``.
"""

import numpy as np

DETERMINISM_CONTRACT = {
    "family": "fragment",
    "dtype": "float64",
    "functions": ["bad_zero_fill_reduceat", "bad_inline_sum",
                  "bad_method_sum", "good_compressed"],
}


def bad_zero_fill_reduceat(c, ok, starts):
    c_w = np.where(ok, c, 0.0)
    return np.add.reduceat(c_w, starts)   # GL901


def bad_inline_sum(c, ok):
    return np.sum(np.where(ok, c, 0.0))   # GL901 (inline operand)


def bad_method_sum(c, ok):
    filled = np.where(ok, c, 0)
    return filled.sum()                   # GL901 (.sum() method)


def good_compressed(c, ok, starts):
    # the sanctioned shape: compress the survivors, then reduce
    kept = c[ok]
    return float(np.sum(kept))
