"""GL11xx negative fixture: every sanctioned form of the same shapes.

Loaded under a durable + annotated pipeline path in
tests/test_analysis.py; no GL11xx code may fire here.
"""

import threading

from galah_tpu.io import atomic
from galah_tpu.obs import timing

GUARDED_BY = {"_state": "LOCK"}

LOCK = threading.Lock()
_state = {}


def append_record(path, rec):
    # the sanctioned durable route: effects stop at io/atomic.py
    atomic.write_json(path, rec, site="fixture")


def rotate_with():
    with LOCK:
        _state.clear()


def rotate_try():
    LOCK.acquire()
    try:
        _state.clear()
    finally:
        LOCK.release()


class _Guard:
    def acquire(self):
        return True

    def __enter__(self):
        # passthrough delegation: the caller owns the release
        return self.acquire()


def _flush_cb(token, path):
    with timing.adopt(token):
        return path


def drain(pool, token, paths):
    for p in paths:
        pool.submit(_flush_cb, token, p)


def consume_windows():
    # incremental consumption of a streamed producer is the contract
    total = 0
    for w in iter_windows():
        total += w
    return total


def iter_windows():
    yield from range(4)
