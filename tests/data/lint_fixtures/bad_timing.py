"""GL7xx fixture: ad-hoc timing a pipeline module must not contain."""

import logging
import time
from time import perf_counter as pc

logger = logging.getLogger(__name__)


def bad_direct():
    t0 = time.perf_counter()          # GL701
    work()
    return time.perf_counter() - t0   # GL701


def bad_aliased():
    import time as _t

    start = _t.time()                 # GL701 (aliased module)
    work()
    dt = pc() - start                 # GL701 (from-import alias)
    logger.info("stage took %.2fs", dt)            # GL702
    logger.debug(f"warmup was {dt:.1f}s overall")  # GL702


def fine():
    # not flagged: monotonic is the deadline/budget clock, sleep is
    # not timing, and a suppressed call documents its justification
    deadline = time.monotonic() + 5.0
    time.sleep(0.1)
    stamp = time.time()  # galah-lint: ignore[GL701] wall-clock stamp
    logger.info("deadline %s stamp %s", deadline, stamp)


def work():
    pass
