"""GL10xx fixture: every pipeline-discipline violation in one file."""

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import jax

# GUARDED_BY puts this module in GL1003's threaded scope.
GUARDED_BY = {"_RESULTS": "_LOCK"}

# GL1005 x2: unknown key "depth"; "missing_stage" is not defined here.
# GL1004: the declared gauge is never emitted anywhere in the file.
PIPELINE_STAGE = {
    "streaming": ["iter_rows", "missing_stage"],
    "occupancy_gauge": "workload.pipeline_occupancy",
    "depth": 4,
}

_LOCK = threading.Lock()
_RESULTS = {}


def iter_rows(paths):
    for p in paths:
        x = compute(p)
        jax.block_until_ready(x)  # GL1002 (host sync in streaming stage)
        yield x


def compute(p):
    return p


def drain_everything(paths):
    rows = list(iter_rows(paths))       # GL1001 (direct materialization)
    stream = iter_rows(paths)
    ordered = sorted(stream)            # GL1001 (via name binding)
    return rows, ordered


def build_handoffs():
    q = queue.Queue()                   # GL1003 (no maxsize)
    sq = queue.SimpleQueue()            # GL1003 (cannot be bounded)
    pool = ThreadPoolExecutor()         # GL1003 (no max_workers)
    return q, sq, pool


def drain_again(paths):
    return tuple(iter_rows(paths))      # GL1001 (tuple materialization)
