"""Seeded GL401/GL402 violations: flag registry drift."""

import os

# GL401: flag that skipped the central registry
typo = os.environ.get("GALAH_TPU_CAHCE")

# GL402: literal default conflicting with the registry's "8"
block = int(os.environ.get("GALAH_TPU_PAIRLIST_BLOCK", "16"))

# negative control: matching literal default is fine
sparse = int(os.environ.get("GALAH_TPU_SPARSE_MIN_N", "1024"))
