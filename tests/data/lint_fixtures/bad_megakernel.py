"""GL1006 fixture: host syncs inside a declared device-round body."""

import jax
import numpy as np

# GL1005: "phantom_fold" is not defined in this module.
PIPELINE_STAGE = {
    "device_round": ["_fold_body", "phantom_fold"],
}


def _fold_body(qi, qj, qv, count):
    arr = np.asarray(qv)                # GL1006 (forces a transfer)
    n = count.item()                    # GL1006 (scalar pull)
    pulled = jax.device_get(qi)         # GL1006
    jax.block_until_ready(qj)           # GL1006
    return arr, n, pulled


def host_wrapper(qv):
    # Unannotated: conversions at the wrapper boundary are the fix,
    # so the very same calls stay silent here.
    return np.asarray(qv), jax.device_get(qv)
