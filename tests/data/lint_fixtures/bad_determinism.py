"""GL9xx fixture: hash-order, narrowing, unseeded RNG, stale contract."""

import random

import numpy as np

DETERMINISM_CONTRACT = {
    "family": "fragment",
    "dtype": "float64",
    "functions": ["bad_narrowing", "gone_function"],  # GL905 (stale)
}


def bad_narrowing(x):
    y = x.astype(np.float32)             # GL903 (astype narrowing)
    z = np.zeros(4, dtype=np.float32)    # GL903 (dtype= kwarg)
    return y, z


def bad_set_order(paths):
    unique = set(paths)
    order = [p for p in unique]          # GL902 (comprehension)
    for p in {"a", "b"}:                 # GL902 (for over set literal)
        order.append(p)
    arr = np.array(unique)               # GL902 (materializes a set)
    return order, arr


def bad_rng(n):
    u = random.random()                  # GL904 (global random state)
    rng = np.random.default_rng()        # GL904 (no seed)
    return u, rng.normal(size=n)


def good_patterns(seed, items):
    rng = np.random.default_rng(seed)    # seeded: clean
    ordered = sorted(set(items))         # sorted set: clean
    return rng, ordered
