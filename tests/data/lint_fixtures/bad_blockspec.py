"""Seeded GL103/GL104 violations: off-quantum BlockSpec dims."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PALLAS_CONTRACT = {
    "bad_tile": {
        "bindings": {"rows": 16},
        "in_dtypes": ["float32"],
        "kernel_fns": ["_k"],
    },
}


def _k(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def bad_tile(x):
    return pl.pallas_call(
        _k,
        grid=(1,),
        in_specs=[
            # lane dim 100 is not a multiple of 128 -> GL103,
            # sublane dim 7 is not a multiple of the f32 quantum -> GL104
            pl.BlockSpec((7, 100), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rows, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),  # noqa: F821
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
    )(x)
