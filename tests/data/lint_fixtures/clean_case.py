"""Negative fixture: a fully contract-compliant module. Every checker
must report zero findings here."""

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 128

PALLAS_CONTRACT = {
    "good_tile": {
        "bindings": {"rows": 8},
        "in_dtypes": ["float32"],
        "kernel_fns": ["_k"],
    },
}


def _k(x_ref, o_ref):
    o_ref[...] = x_ref[...] * jnp.float32(2)


def good_tile(x):
    return pl.pallas_call(
        _k,
        grid=(1,),
        in_specs=[pl.BlockSpec((rows, TILE), lambda i: (i, 0),  # noqa: F821
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((rows, TILE), lambda i: (i, 0),  # noqa: F821
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, TILE), jnp.float32),
    )(x)


@jax.jit
def good_jit(x):
    if x.shape[0] > 2:
        return jnp.sum(x)
    return x


def read_registered_flag():
    from galah_tpu.config import env_value

    return env_value("GALAH_TPU_PAIRLIST_BLOCK")
