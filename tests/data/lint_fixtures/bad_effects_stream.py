"""GL1103 fixture (loaded as a pipeline-scope path).

tests/test_analysis.py loads this under ``galah_tpu/fleet/stage.py``
and asserts exact lines; keep the layout stable. The materialization
happens one call level away from the producer, so lexical GL1001
stays silent.
"""


def _collect(items):
    # the hidden materializer: GL1001 never sees the producer from
    # here, and the call site never sees the list()
    return list(items)                  # line 13: the drain


def run_windows():
    return _collect(iter_windows())     # line 17: GL1103 anchors here


def iter_windows():
    yield from range(4)
