"""Fixture: ad-hoc device-cost introspection in a pipeline module.

Loaded by tests/test_analysis.py at a synthetic galah_tpu/ops/ path;
never imported. GL703 must flag the direct memory_stats() and
cost_analysis() calls; the suppressed line must survive with a
justification; the unrelated same-name *attribute access* (no call)
and a method defined locally must not fire.
"""
import jax


def snoop(fn, x):
    dev = jax.devices()[0]
    stats = dev.memory_stats()  # line 14: GL703
    compiled = fn.lower(x).compile()
    costs = compiled.cost_analysis()  # line 16: GL703
    ok = dev.memory_stats  # attribute access only: no finding
    # galah-lint: ignore[GL703] one-off capacity probe, not telemetry
    probe = dev.memory_stats()
    return stats, costs, ok, probe


class NotADevice:
    def memory_stats(self):  # defining the method is fine
        return {}
