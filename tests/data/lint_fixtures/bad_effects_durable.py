"""GL1102/GL1104/GL1105 fixture (loaded as a durable, annotated path).

tests/test_analysis.py loads this under ``galah_tpu/obs/ledger.py``
(a fs_check.DURABLE_MODULES entry, with GUARDED_BY making it an
annotated threaded module) and asserts exact lines; keep the layout
stable.
"""

import threading
import time

GUARDED_BY = {"_state": "LOCK"}

LOCK = threading.Lock()
_state = {}


def _dump(path, payload):
    # the hidden write: one helper level around open() defeats the
    # lexical GL806 file check
    with open(path, "w") as fh:         # line 21: the write sink
        fh.write(payload)


def append_record(path, rec):
    _dump(path, rec)                    # line 26: GL1102 anchors here


def rotate():
    LOCK.acquire()                      # line 30: GL1104 (no finally)
    _state.clear()
    LOCK.release()


def _flush_cb(path):
    time.sleep(0.1)                     # effect, and never adopts
    return path


def drain(pool, paths):
    for p in paths:
        pool.submit(_flush_cb, p)       # line 42: GL1105 anchors here
