"""Seeded GL806 violations: hand-rolled durable writes that bypass
io/atomic.py. Loaded by test_analysis.py with its path overridden to a
DURABLE_MODULES entry; never scanned in place (data dir is excluded)."""

import json
import os
import tempfile


def store_entry(path, payload):
    # write-mode open(): the pre-atomic idiom, torn on a mid-write kill
    with open(path, "w") as f:
        json.dump(payload, f)


def append_line(path, record):
    # append mode is also a durable write
    with open(path, mode="a") as f:
        f.write(json.dumps(record) + "\n")


def tmp_rename(path, data):
    # the hand-rolled tmp+rename idiom: no fsync, no dir-fsync, and
    # invisible to the GALAH_FI filesystem faults
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def read_back(path):
    # read-mode opens are fine: recovery code reads everything
    with open(path) as f:
        return f.read()
