"""GL1101 fixture: the lexical GL1006 blind spot.

The device-round body never mentions a sync call itself — it routes
the scalar pull through a local helper — so lexical GL1006 stays
silent while the interprocedural GL1101 must report the body with the
full witness chain. Line numbers are asserted exactly in
tests/test_analysis.py; keep the layout stable.
"""

PIPELINE_STAGE = {
    "device_round": ["_fold_round"],
}


def _pull_scalar(count):
    # the hidden sink: one helper level is all it takes to defeat a
    # per-function lexical rule
    return count.item()                 # line 18: the sync sink


def _fold_round(qi, qv, count):
    n = _pull_scalar(count)             # line 22: GL1101 anchors here
    return qi, qv, n
