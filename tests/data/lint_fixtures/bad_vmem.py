"""Seeded GL105 violation: resident blocks far beyond the VMEM budget."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PALLAS_CONTRACT = {
    "huge_tile": {
        # 4096 x 4096 f32 in + out + scratch = 3 x 64 MiB, way past
        # the 16 MiB x 0.5 budget -> GL105
        "bindings": {"n": 4096},
        "in_dtypes": ["float32"],
        "kernel_fns": ["_k"],
    },
}


def _k(x_ref, o_ref, s_ref):
    o_ref[...] = x_ref[...]


def huge_tile(x):
    return pl.pallas_call(
        _k,
        grid=(1,),
        in_specs=[pl.BlockSpec((n, n), lambda i: (i, 0),  # noqa: F821
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((n, n), lambda i: (i, 0),  # noqa: F821
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((4096, 4096), jnp.float32),
        scratch_shapes=[pltpu.VMEM((n, n), jnp.float32)],  # noqa: F821
    )(x)
