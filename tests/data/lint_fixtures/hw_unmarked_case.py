"""Seeded GL601 violation: hardware-only tests missing slow/hardware
markers. Imports the quarantined Mosaic kernel, which makes any
test_*.py module hardware-only."""

import pytest

from galah_tpu.ops import pallas_sketch


def test_kernel_on_hardware():
    assert pallas_sketch is not None


@pytest.mark.parametrize("n", [1, 2])
def test_kernel_cases(n):
    assert n > 0


@pytest.mark.slow
def test_properly_marked():
    pass
