"""Seeded GL101 violation: a pallas_call with no PALLAS_CONTRACT."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _k(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def uncontracted_tile(x):
    return pl.pallas_call(
        _k,
        grid=(1,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )(x)
