"""GL8xx fixture: every concurrency-discipline violation in one file."""

import threading

# "Cls.attr" keys guard instance state; bare keys guard module globals.
GUARDED_BY = {
    "Registry._items": "Registry._lock",
    "_CACHE": "_LOCK",
}
LOCK_ORDER = ["_LOCK_A", "_LOCK_B"]

_LOCK = threading.Lock()
_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()
_CACHE = {}


class Registry:
    def __init__(self):
        self._items = []          # clean: construction is exempt
        self._lock = threading.Lock()

    def good_add(self, item):
        with self._lock:
            self._items.append(item)

    def bad_add(self, item):
        self._items.append(item)  # GL801 (mutating call, no lock)

    def bad_assign(self):
        self._items = []          # GL801 (rebind outside lock)


def bad_global_write(key):
    _CACHE[key] = 1               # GL801 (guarded global, no lock)


def bad_order():
    with _LOCK_B:
        with _LOCK_A:             # GL802 (LOCK_ORDER says A first)
            pass


def self_deadlock():
    with _LOCK:
        with _LOCK:               # GL803 (re-acquire held Lock)
            pass


def plain_worker():
    return 1


def bad_spawns(pool):
    pool.submit(plain_worker)                  # GL804 (no adoption)
    t = threading.Thread(target=plain_worker)  # GL804
    t.start()
