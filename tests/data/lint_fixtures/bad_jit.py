"""Seeded GL2xx/GL3xx violations inside jitted bodies."""

import functools
import os

import jax
import numpy as np


@jax.jit
def host_syncs(x):
    # GL203: python control flow on a tracer
    if x:
        # GL201: host cast of a tracer
        return float(x)
    # GL202: silent device->host pull
    y = np.asarray(x)
    # GL201: .item() inside a jitted body
    return y, x.item()


@functools.partial(jax.jit, static_argnames=("mode",))
def env_in_jit(x, mode="fast"):
    # GL301: environment read frozen at trace time
    flag = os.environ.get("GALAH_TPU_DENSE_PAIRS", "")
    return x if flag else -x


# GL302: unhashable default on a static argument
@functools.partial(jax.jit, static_argnames=("opts",))
def unhashable_static(x, opts=[1, 2]):
    return x


@jax.jit
def clean_shapes(x):
    # negative control: .shape access on a tracer is static and exempt
    if x.shape[0] > 4:
        return x[:4]
    return x
