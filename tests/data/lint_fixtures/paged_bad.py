"""GL1007 fixture: a paged band walk that retains gathered bands.

Loaded with path="galah_tpu/ops/bucketing.py" so the PAGED_MODULES
registry arms the rule for bucketed_threshold_pairs(). Three seeded
violations: an in-loop append of the gathered submatrix (lexical),
a use of the gather-bound name after the loop (lexical), and a
gather value handed to a helper chain that stores it in a module
global (interprocedural — invisible to the lexical arm)."""

_STASH = []


def _keep_band(sub):
    _STASH.append(sub)


def _fold(sub, acc):
    _keep_band(sub)
    return len(acc)


def _reduce(sub):
    return sub.sum()


def bucketed_threshold_pairs(mat, bands):
    kept = []
    total = 0
    for b in bands:
        sub = mat.band_gather(b)
        kept.append(sub)
        total += _reduce(sub)
        total += _fold(mat.gather(b), kept)
    return total, sub
