"""Clean negative for GL8xx: annotated module with full discipline."""

import threading

GUARDED_BY = {
    "Store._data": "Store._lock",
    "_REGISTRY": "_LOCK_A",
}
LOCK_ORDER = ["_LOCK_A", "_LOCK_B"]

_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()
_REGISTRY = {}


class Store:
    def __init__(self):
        self._data = {}
        self._lock = threading.Lock()

    def put(self, key, value):
        with self._lock:
            self._data[key] = value

    def snapshot(self):
        with self._lock:
            return dict(self._data)


def register(name, value):
    with _LOCK_A:
        _REGISTRY[name] = value


def nested_in_declared_order():
    with _LOCK_A:
        with _LOCK_B:  # matches LOCK_ORDER: A is outermost
            pass


def adopted_spawns(pool):
    from galah_tpu.utils import timing

    token = timing.stage_token()

    def worker():
        with timing.adopt(token):
            return 1

    pool.submit(worker)
    t = threading.Thread(target=worker)
    t.start()
