"""Seeded GL106 violations: 64-bit dtypes at and inside the kernel."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PALLAS_CONTRACT = {
    "u64_tile": {
        "bindings": {},
        # u64 at the input boundary -> GL106
        "in_dtypes": ["uint64"],
        "kernel_fns": ["_k64"],
    },
}


def _k64(x_ref, o_ref):
    # 64-bit constant reference inside a kernel body -> GL106
    o_ref[...] = x_ref[...].astype(jnp.int64)


def u64_tile(x):
    return pl.pallas_call(
        _k64,
        grid=(1,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        # u64 out_shape -> GL106
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.uint64),
    )(x)
