"""Clean negative for GL10xx: a streaming stage with full discipline."""

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

from galah_tpu.obs import metrics

GUARDED_BY = {"_RESULTS": "_LOCK"}

PIPELINE_STAGE = {
    "streaming": ["iter_rows"],
    "occupancy_gauge": "workload.pipeline_occupancy",
}

_LOCK = threading.Lock()
_RESULTS = {}


def iter_rows(paths):
    for p in paths:
        yield compute(p)


def compute(p):
    return p


def consume_incrementally(paths):
    total = 0
    for row in iter_rows(paths):
        total += row
    metrics.pipeline_occupancy(0.9)  # satisfies the gauge contract
    return total


def bounded_slice(paths):
    # materializing a plain (non-streamed) call is fine
    return list(sorted_paths(paths))


def sorted_paths(paths):
    return sorted(paths)


def build_handoffs():
    q = queue.Queue(maxsize=8)
    pool = ThreadPoolExecutor(max_workers=2)
    return q, pool
