"""GL704 fixture: a pipeline-stage module that hand-rolls its queue
timing instead of emitting flow spans through obs/flow.py."""

import time
from time import monotonic as mono

# GL704 (anchored here): PIPELINE_STAGE declared, obs.flow never used.
PIPELINE_STAGE = {
    "streaming": ["iter_rows"],
    "occupancy_gauge": "workload.pipeline_occupancy",
}


def iter_rows(blocks):
    wait_s = 0.0
    for b in blocks:
        t0 = time.monotonic()
        item = next(b)
        wait_s += time.monotonic() - t0   # GL704 (hand-rolled wait)
        yield item, wait_s


def drain(stream):
    waited = mono()                       # GL704 (aliased from-import)
    total_wait = 0.0
    for _ in stream:
        total_wait = mono() - waited      # GL704 (plain assign)
    budget_left = 5.0 - (mono() - waited)  # not a wait name: no finding
    return total_wait, budget_left
