"""Persistent sketch index: build/insert/query roundtrip, versioned
generations, tombstone repair, fsck, and preemption/resume.

The central claim under test is byte-identity (docs/index.md): an
index grown by `insert` holds exactly the bytes a from-scratch `build`
over the same quality order writes, and its re-derived clusters equal
the cluster engine's output on the same corpus. Everything else —
stale readers, local tombstone repair, fsck's problem/warning split,
exit-75 preemption with `--resume` convergence, and the
"resketch only the new genomes" counter — rides on that foundation.
"""

import json
import os
import shutil

import numpy as np
import pytest

from galah_tpu.backends import MinHashPreclusterer
from galah_tpu.cluster import cluster
from galah_tpu.index import incremental
from galah_tpu.index.store import IndexStore, fsck
from galah_tpu.io import diskcache
from galah_tpu.resilience import interrupt

BASES = np.array(list("ACGT"))


def _write(path, codes, line=70):
    seq = "".join(BASES[codes])
    with open(path, "w") as f:
        f.write(">contig1\n")
        for i in range(0, len(seq), line):
            f.write(seq[i:i + line] + "\n")


def _dir_bytes(path):
    """Committed-artifact bytes, keyed by name. interruptions.jsonl is
    the one legitimately run-dependent file (it records the kills)."""
    return {
        name: open(os.path.join(path, name), "rb").read()
        for name in sorted(os.listdir(path))
        if name != "interruptions.jsonl"
    }


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """4 planted families x 3 members (~0.5% within-family divergence)
    plus three unrelated singletons for insert/query probes."""
    root = tmp_path_factory.mktemp("index_corpus")
    rng = np.random.default_rng(17)
    length = 10_000
    fams = []
    for fam in range(4):
        base = rng.integers(0, 4, size=length)
        members = []
        for m in range(3):
            codes = base.copy()
            if m:
                sites = rng.random(length) < 0.005
                codes[sites] = (codes[sites] + rng.integers(
                    1, 4, size=int(sites.sum()))) % 4
            p = str(root / f"fam{fam}_m{m}.fna")
            _write(p, codes)
            members.append(p)
        fams.append(members)
    extras = []
    for i in range(3):
        p = str(root / f"solo{i}.fna")
        _write(p, rng.integers(0, 4, size=length))
        extras.append(p)
    return fams, extras


@pytest.fixture(scope="module")
def grown(corpus, tmp_path_factory):
    """Build over 8 genomes, insert 4 (one family-joiner + one whole
    new family) — the pristine incremental index every test copies."""
    fams, _ = corpus
    root = tmp_path_factory.mktemp("index_grown")
    cache = str(root / "cache")
    base = fams[0][:2] + fams[1] + fams[2]
    inserted = [fams[0][2]] + fams[3]
    idx_dir = str(root / "idx")
    incremental.build(idx_dir, base, ani=0.95, precluster_ani=0.90,
                      cache_dir=cache, threads=2)
    info = incremental.insert(IndexStore(idx_dir), inserted,
                              cache_dir=cache, threads=2)
    assert info["inserted"] == 4
    assert info["generation"] == 2
    return {"idx": idx_dir, "cache": cache, "base": base,
            "inserted": inserted, "full": base + inserted}


def test_roundtrip_byte_identical_to_from_scratch(grown, tmp_path):
    scratch = str(tmp_path / "scratch")
    incremental.build(scratch, grown["full"], ani=0.95,
                      precluster_ani=0.90, cache_dir=grown["cache"],
                      threads=2)
    got = _dir_bytes(grown["idx"])
    want = _dir_bytes(scratch)
    # the only sanctioned divergence: the grown index is at
    # generation 2 and carries gen-000001.json from its build
    del got["MANIFEST.json"], want["MANIFEST.json"]
    gen2 = got.pop("gen-000002.json")
    gen1 = got.pop("gen-000001.json")
    want_gen1 = want.pop("gen-000001.json")
    assert json.loads(gen2)["n_genomes"] == len(grown["full"])
    assert got == want
    # the grown decision state equals the from-scratch one exactly,
    # generation number aside
    g2, w1 = json.loads(gen2), json.loads(want_gen1)
    del g2["generation"], w1["generation"]
    assert g2 == w1
    assert json.loads(gen1)["n_genomes"] == len(grown["base"])


def test_clusters_match_engine(grown):
    """The persisted decisions re-derive the cluster engine's exact
    output (order included) on the same quality-ordered corpus."""
    state = IndexStore(grown["idx"]).load()
    pre = MinHashPreclusterer(
        min_ani=0.90, cache=diskcache.get_cache(grown["cache"]),
        threads=2)
    engine = cluster(grown["full"], pre,
                     incremental.SketchANIClusterer(0.95))
    got = incremental.clusters_from_state(state)
    assert [sorted(c) for c in got] == [sorted(c) for c in engine]
    assert got == [list(c) for c in engine]


def test_query_member_and_novel(grown, corpus):
    _, extras = corpus
    idx = IndexStore(grown["idx"])
    state = idx.load()
    joiner = grown["inserted"][0]  # fam0_m2 — a committed member
    res = incremental.query(idx, [joiner, extras[2]],
                            cache_dir=grown["cache"])
    member, novel = res
    assert member["decision"] == "member"
    g = state.genomes.index(joiner)
    assert member["rep"] == state.genomes[state.membership[g]]
    assert member["ani"] >= 0.95
    assert novel["decision"] == "novel"
    assert novel["rep"] is None
    # read-only: no generation bump, no new genome records
    assert idx.generation() == 2
    assert idx.reload().n_genomes == state.n_genomes


def test_generation_bump_and_stale_reader(grown, corpus, tmp_path):
    _, extras = corpus
    d = str(tmp_path / "idx")
    shutil.copytree(grown["idx"], d)
    reader = IndexStore(d)
    old = reader.load()
    assert old.generation == 2
    info = incremental.insert(IndexStore(d), [extras[0]],
                              cache_dir=grown["cache"])
    assert info["generation"] == 3
    # the stale reader keeps serving its loaded generation until it
    # explicitly reloads the commit pointer
    assert reader.load().generation == 2
    fresh = reader.reload()
    assert fresh.generation == 3
    assert fresh.n_genomes == old.n_genomes + 1


def test_insert_skips_known_and_resketches_only_new(grown, corpus,
                                                    tmp_path):
    from galah_tpu.obs import metrics as obs_metrics

    _, extras = corpus
    d = str(tmp_path / "idx")
    shutil.copytree(grown["idx"], d)

    def computed():
        snap = obs_metrics.snapshot().get("sketch.minhash_computed")
        return int(snap["value"]) if snap else 0

    before = computed()
    info = incremental.insert(
        IndexStore(d), [grown["inserted"][0], extras[0], extras[1]],
        cache_dir=str(tmp_path / "coldcache"))
    assert info["skipped"] == 1
    assert info["inserted"] == 2
    # a COLD cache dir, yet only the genuinely new genomes were
    # sketched — known paths never reach the sketch stage at all
    assert computed() - before == 2
    # idempotence: replaying the same insert commits nothing
    info = incremental.insert(
        IndexStore(d), [grown["inserted"][0], extras[0], extras[1]],
        cache_dir=str(tmp_path / "coldcache"))
    assert info["inserted"] == 0
    assert info["skipped"] == 3
    assert info["generation"] == 3


def test_remove_tombstone_and_reelection(grown, tmp_path):
    d = str(tmp_path / "idx")
    shutil.copytree(grown["idx"], d)
    idx = IndexStore(d)
    state = idx.load()
    rep = next(r for r in state.reps
               if sum(1 for v in state.membership.values()
                      if v == r) >= 2)
    members = sorted(g for g, v in state.membership.items() if v == rep)
    info = incremental.remove(idx, state.genomes[rep])
    assert info["removed"] == rep
    assert info["reelected"] == members[0]
    state = idx.load()
    assert rep in state.tombstones
    assert rep not in state.reps
    assert members[0] in state.reps
    for g in members[1:]:
        assert state.membership[g] == members[0]
    audit = fsck(d)
    assert audit["ok"], audit["problems"]
    assert audit["tombstones"] == 1
    # removing a plain member just tombstones it
    info = incremental.remove(idx, state.genomes[members[1]])
    assert info["reelected"] is None
    with pytest.raises(ValueError, match="not a live genome"):
        incremental.remove(idx, state.genomes[rep])


def test_fsck_truncated_and_flipped_records(grown, tmp_path):
    # torn tail PAST the commit point: warning, still ok
    d = str(tmp_path / "tail")
    shutil.copytree(grown["idx"], d)
    with open(os.path.join(d, "pairs.jsonl"), "ab") as f:
        f.write(b'{"i": 0, "j": 99, "ani": 0.99}|deadbeef\n')
    audit = fsck(d)
    assert audit["ok"], audit["problems"]
    assert any("torn" in w for w in audit["warnings"])

    # truncation INSIDE the committed region: problem
    d = str(tmp_path / "trunc")
    shutil.copytree(grown["idx"], d)
    fn = os.path.join(d, "sketches.jsonl")
    size = os.path.getsize(fn)
    with open(fn, "rb+") as f:
        f.truncate(size // 2)
    audit = fsck(d)
    assert not audit["ok"]
    assert any("sketches.jsonl" in p for p in audit["problems"])

    # a single flipped byte in a committed record: the frame checksum
    # rejects it, so the committed count comes up short — problem
    d = str(tmp_path / "flip")
    shutil.copytree(grown["idx"], d)
    fn = os.path.join(d, "genomes.jsonl")
    with open(fn, "rb") as f:
        raw = bytearray(f.read())
    mid = raw.index(b'"path"') + 10
    raw[mid] ^= 0xFF
    with open(fn, "wb") as f:
        f.write(raw)
    audit = fsck(d)
    assert not audit["ok"]
    assert any("genomes.jsonl" in p for p in audit["problems"])


def test_cli_insert_preempted_then_resume_converges(grown, corpus,
                                                    tmp_path):
    """SIGTERM-style stop mid-insert: the CLI exits 75 with the index
    loadable at the prior generation, and `--resume` completes to the
    exact bytes an uninterrupted insert writes."""
    from galah_tpu.cli import main
    from galah_tpu.resilience.interrupt import EXIT_PREEMPTED

    _, extras = corpus
    d = str(tmp_path / "idx")
    ref = str(tmp_path / "ref")
    shutil.copytree(grown["idx"], d)
    shutil.copytree(grown["idx"], ref)
    incremental.insert(IndexStore(ref), extras[:2],
                       cache_dir=grown["cache"])

    orig = incremental.iter_insert_sketches

    def tripping(paths, sk_store, threads=1):
        for p, sk in orig(paths, sk_store, threads=threads):
            yield p, sk
            interrupt.request_stop("TEST")

    argv = ["index", "--index-dir", d, "insert",
            "-f", extras[0], extras[1],
            "--sketch-cache", grown["cache"], "--batch", "1"]
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(incremental, "iter_insert_sketches", tripping)
        try:
            rc = main(argv)
        finally:
            interrupt.reset()
    assert rc == EXIT_PREEMPTED
    idx = IndexStore(d)
    assert idx.generation() == 2  # still the pre-insert commit
    assert idx.load_interruptions(), "preemption chain not recorded"
    audit = fsck(d)
    assert audit["ok"], audit["problems"]
    assert any("uncommitted tail" in w for w in audit["warnings"])

    try:
        rc = main(argv + ["--resume"])
    finally:
        interrupt.reset()
    assert rc == 0
    assert IndexStore(d).generation() == 3
    assert _dir_bytes(d) == _dir_bytes(ref)


def test_build_refuses_param_drift(grown, tmp_path):
    with pytest.raises(ValueError, match="already built"):
        incremental.build(grown["idx"], grown["base"], ani=0.95,
                          precluster_ani=0.90,
                          cache_dir=grown["cache"])
    with pytest.raises(ValueError, match="different parameters"):
        incremental.build(grown["idx"], grown["base"], ani=0.97,
                          precluster_ani=0.90,
                          cache_dir=grown["cache"])
    with pytest.raises(ValueError, match="no index at"):
        IndexStore(str(tmp_path / "nothing"))
