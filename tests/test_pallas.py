"""Pallas TPU kernels, run in interpreter mode on the CPU test mesh.

On a real TPU backend the same kernels compile via Mosaic (use_pallas
auto-enables, ops/hll.py); tests here pin numerical parity between the
kernels and their XLA reference formulations.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from galah_tpu.ops import hll
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pallas_hll import hll_union_stats_tile
from galah_tpu.ops.pallas_pairwise import tile_stats_pallas
from galah_tpu.ops.pairwise import tile_stats


@pytest.mark.parametrize("br,bc,m", [(16, 24, 4096), (8, 8, 1024)])
def test_hll_union_stats_parity(br, bc, m):
    rng = np.random.default_rng(0)
    regs_r = rng.integers(0, 20, size=(br, m)).astype(np.uint8)
    regs_c = rng.integers(0, 20, size=(bc, m)).astype(np.uint8)
    pr = jnp.asarray(np.exp2(-regs_r.astype(np.float32)))
    pc = jnp.asarray(np.exp2(-regs_c.astype(np.float32)))

    ps, z = hll_union_stats_tile(pr, pc, chunk=min(1024, m),
                                 interpret=True)

    union = np.maximum(regs_r[:, None, :], regs_c[None, :, :])
    ps_ref = np.exp2(-union.astype(np.float64)).sum(-1)
    z_ref = (union == 0).sum(-1).astype(np.float64)
    np.testing.assert_allclose(np.asarray(ps), ps_ref, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(z), z_ref)


def _rand_sketches(rng, n, width, n_valid_max):
    mat = np.full((n, width), np.uint64(SENTINEL), dtype=np.uint64)
    for i in range(n):
        nv = int(rng.integers(n_valid_max // 2, n_valid_max + 1))
        v = np.unique(rng.integers(0, 1 << 64, size=nv, dtype=np.uint64))
        mat[i, :v.shape[0]] = v
    return mat


# One small interpret parity rides the default tier; the larger
# widths are tracing-bound in interpret mode (cost scales with
# K_pad/8 unrolled lane loops) and ride the slow tier.
@pytest.mark.parametrize("width,sketch_size", [
    pytest.param(1000, 1000, marks=pytest.mark.slow),
    pytest.param(512, 500, marks=pytest.mark.slow),
    (256, 250)])
def test_minhash_pair_stats_parity(width, sketch_size):
    """tile_stats_pallas must be bit-identical to the XLA searchsorted
    path on (common, total) — including short sketches, sentinel padding
    and heavy overlap."""
    rng = np.random.default_rng(7)
    rows = _rand_sketches(rng, 5, width, sketch_size)
    cols = _rand_sketches(rng, 6, width, sketch_size)
    cols[0] = rows[0]                       # identical pair
    half = sketch_size // 2
    cols[1, :half] = rows[1, :half]         # heavy overlap
    cols[1].sort()

    c_p, t_p = tile_stats_pallas(jnp.asarray(rows), jnp.asarray(cols),
                                 sketch_size, interpret=True)
    c_x, t_x = tile_stats(jnp.asarray(rows), jnp.asarray(cols),
                          sketch_size, 21)
    np.testing.assert_array_equal(np.asarray(c_p), np.asarray(c_x))
    np.testing.assert_array_equal(np.asarray(t_p), np.asarray(t_x))
    assert int(np.asarray(c_p)[0, 0]) > 0


def test_threshold_pairs_pallas_interpret_matches_xla():
    """End-to-end hll_threshold_pairs with the pallas path (interpret via
    monkeypatched kernel default) equals the XLA path."""
    rng = np.random.default_rng(5)
    n, p = 40, 10
    mat = np.zeros((n, 1 << p), dtype=np.uint8)
    for i in range(n):
        h = rng.integers(0, 1 << 63, size=50_000, dtype=np.uint64) * 2 + 1
        mat[i] = np.asarray(hll._hll_update(
            jnp.zeros((1 << p,), dtype=jnp.uint8), jnp.asarray(h), p))
    mat[33] = mat[7]

    import galah_tpu.ops.pallas_hll as pallas_hll

    orig = pallas_hll.hll_union_stats_tile
    pallas_hll.hll_union_stats_tile = (
        lambda r, c, chunk=1024, interpret=False:
        orig(r, c, chunk=chunk, interpret=True))
    try:
        via_pallas = hll.hll_threshold_pairs(mat, k=21, min_ani=0.95,
                                             use_pallas=True)
    finally:
        pallas_hll.hll_union_stats_tile = orig
    via_xla = hll.hll_threshold_pairs(mat, k=21, min_ani=0.95,
                                      use_pallas=False)
    assert set(via_pallas) == set(via_xla)
    assert (7, 33) in via_pallas
    for key in via_pallas:
        assert abs(via_pallas[key] - via_xla[key]) < 1e-5


@pytest.mark.slow
def test_minhash_pair_stats_range_skip_parity():
    """The range-skip variant (prefix bulk-count + suffix skip over
    sorted b-chunks) must stay bit-identical to the XLA path."""
    rng = np.random.default_rng(21)
    rows = _rand_sketches(rng, 6, 1000, 1000)
    cols = _rand_sketches(rng, 7, 1000, 1000)
    cols[2] = rows[3]
    c_p, t_p = tile_stats_pallas(jnp.asarray(rows), jnp.asarray(cols),
                                 1000, interpret=True, range_skip=True)
    c_x, t_x = tile_stats(jnp.asarray(rows), jnp.asarray(cols),
                          1000, 21)
    np.testing.assert_array_equal(np.asarray(c_p), np.asarray(c_x))
    np.testing.assert_array_equal(np.asarray(t_p), np.asarray(t_x))
