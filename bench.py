"""Benchmark harness: device throughput vs an honest CPU baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Headline metric: all-pairs MinHash ANI throughput (genome-pairs/sec) —
the production sparse pair extraction (ops/pairwise.threshold_pairs)
replacing the reference's host O(N^2) pair loop (reference:
src/finch.rs:53-73). On TPU this runs the Mosaic pair-stats kernel
(ops/pallas_pairwise.py); the result dict lands on host, so the timing
includes real device->host materialization.

Extra stages (reported under "stages", each guarded so one failure
never loses the line):
  * pairwise_xla — the same extraction on the XLA searchsorted path;
  * sketch_bp_per_sec — MinHash sketching on real FASTA bytes
    (the abisko4 MAGs when available; reference analog: finch
    sketch_files, src/finch.rs:47);
  * e2e — full cluster() (ingest -> sketch -> pairwise -> greedy ->
    exact ANI) on synthetic planted families, BASELINE.md rung-1 class.

Baseline: the SAME merged-bottom-k pair computation compiled by XLA on
the host CPU (multi-threaded) in a subprocess. There is no Rust
toolchain in this image, so the reference's compiled-Rust path cannot be
timed directly; XLA-CPU is the strongest available stand-in and is
labeled as such ("baseline" field). This replaces round 1's
single-threaded pure-Python loop, which overstated speedups.

Robustness contract (the driver runs this unattended): the TPU backend
is probed in a SUBPROCESS with a bounded timeout and one retry, every
stage has a SIGALRM watchdog, and the JSON line is always printed —
with an "errors" field when something failed.
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

K = 21
SKETCH_SIZE = 1000
PRODUCTION_N = 4096  # bench_production workload size, reported as n_genomes

_CPU_BASELINE_CODE = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")
import time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from galah_tpu.ops.pairwise import tile_stats

n, K_, kmer = 256, %d, %d
rng = np.random.default_rng(0)
mat = rng.integers(0, 1 << 63, size=(n, K_), dtype=np.uint64)
mat.sort(axis=1)
jm = jnp.asarray(mat)
jax.block_until_ready(tile_stats(jm, jm, K_, kmer))  # compile + warm
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    jax.block_until_ready(tile_stats(jm, jm, K_, kmer))
    best = min(best, time.perf_counter() - t0)
print("RESULT", n * n / best)
"""

_C_BASELINE_CODE = r"""
import os, time
os.environ["JAX_PLATFORMS"] = "cpu"   # package imports must not touch
import jax                            # the (possibly wedged) TPU tunnel
jax.config.update("jax_platforms", "cpu")
import numpy as np
from galah_tpu.ops._cpairstats import threshold_pairs_c

n, K_, kmer = 256, %d, %d
rng = np.random.default_rng(0)
mat = rng.integers(0, 1 << 63, size=(n, K_), dtype=np.uint64)
mat.sort(axis=1)
threshold_pairs_c(mat, K_, kmer, 0.95)  # warm
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    threshold_pairs_c(mat, K_, kmer, 0.95)
    best = min(best, time.perf_counter() - t0)
# Credit the C walk with the full n*n square: it decides every
# unordered pair once where the tiled passes evaluate both orders, and
# the headline uses the n*n convention — same units, conservative for
# the reported speedup.
print("RESULT", n * n / best)
"""

_CPU_PRODUCTION_CODE = r"""
import os, time
os.environ["JAX_PLATFORMS"] = "cpu"   # package imports must not touch
import jax                            # the (possibly wedged) TPU tunnel
jax.config.update("jax_platforms", "cpu")
import bench
print("RESULT", bench.bench_production())
"""

_PROBE_CODE = """
import jax
devs = jax.devices()
assert devs
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
print("RESULT", float((x @ x).sum()))
"""


class StageTimeout(Exception):
    pass


@contextlib.contextmanager
def watchdog(seconds):
    """SIGALRM guard: a wedged device call raises instead of hanging."""
    def handler(signum, frame):
        raise StageTimeout(f"stage exceeded {seconds}s")

    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(int(seconds))
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def run_sub(code, timeout):
    """Run python -c `code` with a hard timeout; return RESULT float."""
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout, cwd=os.path.dirname(os.path.abspath(__file__)))
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT"):
            return float(line.split()[1])
    raise RuntimeError(
        f"subprocess rc={proc.returncode}: {proc.stderr[-500:]}")


def probe_backend(timeout=None, retry_timeout=None):
    """True iff a device backend comes up and multiplies in a subprocess.

    The first attempt defaults to GALAH_BENCH_PROBE_TIMEOUT (420 s,
    matching tests/test_tpu_hw.py's probe allowance — the bench must
    not give up on a tunnel the test harness would still reach; a slow
    axon attach can take minutes after an outage). The retry gets a
    quarter of that so a dead tunnel costs at most ~1.25x the budget
    before the honest CPU fallback.

    Returns ``(ok, reason, detail)``. ``reason`` is a single TOKEN
    (`probe-timeout` or the exception type name, never whitespace or a
    command repr) — it is what lands in the BENCH errors array, where
    downstream grep/ledger tooling treats each error as one
    space-delimited `key=value` line. ``detail`` carries the longer
    one-line text (timeout budget / first 200 chars of the message)
    for the structured `backend_reason_detail` field only."""
    from galah_tpu.config import env_value

    if timeout is None:
        timeout = float(env_value("GALAH_BENCH_PROBE_TIMEOUT"))
    if retry_timeout is None:
        retry_timeout = max(30.0, timeout / 4.0)
    reason = detail = None
    for t in (timeout, retry_timeout):
        try:
            run_sub(_PROBE_CODE, t)
            return True, None, None
        except subprocess.TimeoutExpired:
            # str(TimeoutExpired) embeds the full subprocess command
            # repr — never let that into reason or detail.
            reason = "probe-timeout"
            detail = f"probe-timeout after {t:.0f}s"
        except Exception as e:  # noqa: BLE001 - report, don't crash
            reason = type(e).__name__
            detail = " ".join(str(e).split())[:200] or reason
    return False, reason, detail


def _sketches(n, sketch_size, seed):
    rng = np.random.default_rng(seed)
    mat = rng.integers(0, 1 << 63, size=(n, sketch_size), dtype=np.uint64)
    mat.sort(axis=1)
    return mat


def bench_extraction(mat, repeats=3, use_pallas=None, dense=True):
    """Headline: the dense pair-extraction kernel, pairs/s.

    `dense` pins GALAH_TPU_DENSE_PAIRS for the calls so the number
    measures the tiled kernel (Mosaic on TPU, with XLA fallback) at
    any N — above the sparse crossover the AUTO production path is the
    screened pipeline, measured separately by bench_production (on
    random sketches the screen finds ~no collisions, which would turn
    this headline into a host-sort benchmark). The dense kernel is the
    apples-to-apples comparison against the n=256 dense CPU baselines.

    threshold_pairs returns its sparse dict on host, so the timing
    inherently includes device->host materialization (the axon tunnel's
    block_until_ready does not actually block, so every bench stage
    must force a transfer).
    """
    from galah_tpu.ops.pairwise import threshold_pairs

    n = mat.shape[0]
    prev = os.environ.get("GALAH_TPU_DENSE_PAIRS")
    if dense:
        os.environ["GALAH_TPU_DENSE_PAIRS"] = "1"
    try:
        threshold_pairs(mat, k=K, min_ani=0.95,
                        use_pallas=use_pallas)  # warmup + compile
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            pairs = threshold_pairs(mat, k=K, min_ani=0.95,
                                    use_pallas=use_pallas)
            best = min(best, time.perf_counter() - t0)
    finally:
        if dense:
            if prev is None:
                os.environ.pop("GALAH_TPU_DENSE_PAIRS", None)
            else:
                os.environ["GALAH_TPU_DENSE_PAIRS"] = prev
    assert isinstance(pairs, dict)
    return (n * n) / best


def bench_production(n=PRODUCTION_N, repeats=2):
    """The AUTO production path above the sparse crossover, pairs/s:
    host collision screen + batched device evaluation of survivors,
    on family-structured sketches (random rows share no hashes, which
    would make the screen trivially empty and the number misleading).
    """
    from galah_tpu.ops.pairwise import threshold_pairs

    rng = np.random.default_rng(5)
    n_fam = n // 4
    base = rng.integers(0, 1 << 62, size=(n_fam, SKETCH_SIZE),
                        dtype=np.uint64)
    mat = np.empty((n, SKETCH_SIZE), dtype=np.uint64)
    for i in range(n):
        row = base[i % n_fam].copy()
        n_mut = int(rng.integers(0, SKETCH_SIZE // 20))
        idx = rng.choice(SKETCH_SIZE, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
        row.sort()
        mat[i] = row
    # Pin the dense override OFF: this stage must measure the sparse
    # production path even if the ambient env carries the dense knob
    # (bench_extraction pins it ON the same way).
    prev = os.environ.pop("GALAH_TPU_DENSE_PAIRS", None)
    try:
        threshold_pairs(mat, k=K, min_ani=0.95)  # warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            pairs = threshold_pairs(mat, k=K, min_ani=0.95)
            best = min(best, time.perf_counter() - t0)
    finally:
        if prev is not None:
            os.environ["GALAH_TPU_DENSE_PAIRS"] = prev
    assert len(pairs) >= n // 4, "family pairs must survive the screen"
    return (n * n) / best


def pick_n(budget_s=25.0, n_max=8192):
    """Calibrate: time a small pass, then choose the largest n whose
    projected runtime fits the budget (never blows the driver timeout)."""
    n0 = 512
    rate = bench_extraction(_sketches(n0, SKETCH_SIZE, seed=9), repeats=1)
    n = n0
    while n < n_max and (2 * n) ** 2 / rate < budget_s:
        n *= 2
    return n


def bench_genomes(count=6):
    """The shared bench corpus: first `count` abisko4 MAGs, ingested.

    Returns (genomes, total_bp); ([], 0) when the fixtures are absent.
    Single definition used by every sketching bench (bench.py stages and
    scripts/bench_sketch_variants.py).
    """
    import glob

    from galah_tpu.io.fasta import read_genome

    paths = sorted(glob.glob(
        "/root/reference/tests/data/abisko4/*.fna"))[:count]
    genomes = [read_genome(p) for p in paths]
    return genomes, sum(int(g.codes.shape[0]) for g in genomes)


def bench_sketching(algo="murmur3"):
    """MinHash sketching throughput on real FASTA bytes, bp/s."""
    from galah_tpu.ops.minhash import sketch_genome_device

    genomes, total_bp = bench_genomes()
    if not genomes:
        return None
    for g in genomes:  # compile every chunk-bucket variant
        sketch_genome_device(g, sketch_size=SKETCH_SIZE, k=K, seed=0,
                             algo=algo)
    t0 = time.perf_counter()
    acc = 0
    for g in genomes:
        s = sketch_genome_device(g, sketch_size=SKETCH_SIZE, k=K,
                                 seed=0, algo=algo)
        acc += int(s.hashes[0]) & 0xFF  # force host materialization
    dt = time.perf_counter() - t0
    assert acc >= 0
    return total_bp / dt


def bench_sketching_batch(algo="murmur3"):
    """Grouped-dispatch batch sketching throughput on real FASTA bytes."""
    from galah_tpu.ops.minhash import sketch_genomes_device_batch

    genomes, total_bp = bench_genomes()
    if not genomes:
        return None
    sketch_genomes_device_batch(genomes, sketch_size=SKETCH_SIZE, k=K,
                                seed=0, algo=algo)  # compile
    t0 = time.perf_counter()
    out = sketch_genomes_device_batch(genomes, sketch_size=SKETCH_SIZE,
                                      k=K, seed=0, algo=algo)
    dt = time.perf_counter() - t0
    assert all(s.hashes.shape[0] > 0 for s in out)
    return total_bp / dt


def _synth_families(n_genomes=48, genome_len=60_000, n_families=12,
                    mut=0.03, seed=7, outdir=None):
    """Plant n_families mutated-copy families; returns FASTA paths.

    Auto-created temp dirs are removed at process exit (unattended
    fallback runs would otherwise accumulate orphaned /tmp trees)."""
    import atexit
    import shutil
    import tempfile

    rng = np.random.default_rng(seed)
    if outdir is None:
        outdir = tempfile.mkdtemp(prefix="galah_bench_")
        atexit.register(shutil.rmtree, outdir, ignore_errors=True)
    alphabet = np.frombuffer(b"ACGT", dtype=np.uint8)
    paths = []
    per = n_genomes // n_families
    for f in range(n_families):
        base = rng.integers(0, 4, size=genome_len)
        for m in range(per):
            seq = base.copy()
            if m > 0:
                sites = rng.random(genome_len) < mut
                seq[sites] = (seq[sites] + rng.integers(
                    1, 4, size=int(sites.sum()))) % 4
            p = os.path.join(outdir, f"fam{f}_m{m}.fna")
            with open(p, "wb") as fh:
                fh.write(b">contig1\n")
                fh.write(alphabet[seq].tobytes())
                fh.write(b"\n")
            paths.append(p)
    return paths


def _synth_repeat_genomes(n_genomes=64, genome_len=100_000,
                          repeat_frac=0.3, n_elements=8,
                          element_len=2000, seed=23, outdir=None):
    """UNRELATED genomes sharing mobile-element-like repeat content —
    the collision screen's adversarial case (uniform-random rungs are
    its best case). Every genome is an independent random backbone
    with ~repeat_frac of its length replaced by elements drawn from
    ONE shared pool of n_elements sequences (element_len bp each), at
    random positions. Genomes therefore share k-mers (the screen sees
    collisions) without sharing ancestry (true ANI across genomes is
    driven by the repeat fraction alone). Returns FASTA paths.
    """
    import atexit
    import shutil
    import tempfile

    rng = np.random.default_rng(seed)
    if outdir is None:
        outdir = tempfile.mkdtemp(prefix="galah_repeat_")
        atexit.register(shutil.rmtree, outdir, ignore_errors=True)
    alphabet = np.frombuffer(b"ACGT", dtype=np.uint8)
    pool = [rng.integers(0, 4, size=element_len)
            for _ in range(n_elements)]
    n_ins = max(int(round(genome_len * repeat_frac / element_len)), 0)
    paths = []
    for g in range(n_genomes):
        backbone_len = genome_len - n_ins * element_len
        backbone = rng.integers(0, 4, size=max(backbone_len, 0))
        # splice elements between backbone chunks at random cut points
        cuts = np.sort(rng.integers(0, max(backbone.shape[0], 1),
                                    size=n_ins))
        parts, prev = [], 0
        for c, e in zip(cuts, rng.integers(0, n_elements, size=n_ins)):
            parts.append(backbone[prev:c])
            parts.append(pool[int(e)])
            prev = c
        parts.append(backbone[prev:])
        seq = np.concatenate(parts) if parts else backbone
        p = os.path.join(outdir, f"rep{g}.fna")
        with open(p, "wb") as fh:
            fh.write(b">contig1\n")
            fh.write(alphabet[seq].tobytes())
            fh.write(b"\n")
        paths.append(p)
    return paths


def bench_e2e(fast=False, paths=None):
    """Full cluster() wall-clock on planted families -> genomes/s.

    With `fast`, runs the validated fast mode (--hash-algorithm tpufast
    --ani-subsample 16), which reproduces the dense goldens on the
    18-MAG campaign (tests/test_campaign_abisko18.py).
    """
    from galah_tpu.api import generate_galah_clusterer

    paths = paths or _synth_families()
    values = {"ani": 95.0, "precluster_ani": 90.0,
              "min_aligned_fraction": 15.0, "fragment_length": 3000,
              "precluster_method": "finch", "cluster_method": "skani",
              "threads": 1}
    if fast:
        values.update(hash_algorithm="tpufast", ani_subsample=16)
    t0 = time.perf_counter()
    clusterer = generate_galah_clusterer(paths, values)
    clusters = clusterer.cluster()
    dt = time.perf_counter() - t0
    assert 1 <= len(clusters) <= len(paths)
    return len(paths) / dt, len(clusters), paths


_T0 = time.monotonic()
# Self-budgeting against the harness's hard stage cap (the campaign
# runner kills bench.py at GALAH_BENCH_STAGE_CAP seconds — a kill
# loses EVERY stage's data, as the 2026-08-01 08:39 capture attempt
# did when a competing tunnel client halved its budget). Each
# optional stage is admitted only if its WORST-CASE watchdog cost
# fits in the remaining budget, so the JSON line always prints.
_STAGE_CAP_S = float(os.environ.get("GALAH_BENCH_STAGE_CAP", 3000))
_HEADROOM_S = 60  # JSON print + interpreter teardown margin


def _remaining() -> float:
    return _STAGE_CAP_S - _HEADROOM_S - (time.monotonic() - _T0)


def _admit(cost_s, label, errors) -> bool:
    """True iff a stage whose watchdog allows up to cost_s seconds
    still fits; records the skip otherwise."""
    rem = _remaining()
    if rem < cost_s:
        errors.append(f"{label}: skipped, worst-case {cost_s:.0f}s "
                      f"> {rem:.0f}s remaining budget")
        return False
    return True


def _admitted_watchdog(cost_s, label, errors):
    """One cost figure drives BOTH the admission check and the
    watchdog, so the two cannot drift apart: returns a watchdog
    context for cost_s, or None when the stage does not fit (the
    skip is recorded)."""
    if not _admit(cost_s, label, errors):
        return None
    return watchdog(cost_s)


def _run_pairlist_variants_stage(stages, errors, interpret=False):
    """Per-strategy pairlist throughput + per-term cost breakdown in a
    subprocess (scripts/bench_pairlist_variants.py). The script is
    self-budgeting under the cost we pass, and the subprocess timeout
    adds slack for interpreter startup — a wedge mid-variant cannot
    take down the bench line. `interpret` records the CPU structural
    run so even a no-tunnel capture documents the strategy matrix."""
    _PAIRLIST_COST = 120 if interpret else 300   # hard <=5 min cap
    if not _admit(_PAIRLIST_COST, "pairlist_variants", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        cmd = [sys.executable,
               os.path.join(here, "scripts",
                            "bench_pairlist_variants.py"),
               "--budget", str(_PAIRLIST_COST - 30)]
        if interpret:
            cmd.append("--interpret")
        proc = subprocess.run(
            cmd, capture_output=True, text=True,
            timeout=_PAIRLIST_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("PAIRLIST_JSON "):
                data = json.loads(line[len("PAIRLIST_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        if interpret:
            data["interpret"] = True
        stages["pairlist_variants"] = data
    except Exception as e:  # noqa: BLE001
        errors.append(f"pairlist_variants: {type(e).__name__}: {e}")


def _run_fragment_variants_stage(stages, errors, interpret=False):
    """Per-strategy fragment-ANI throughput + packing-waste breakdown
    in a subprocess (scripts/bench_fragment_variants.py) — the
    exact-stage twin of the pairlist matrix: pallas pack sweep with
    launch/occupancy counters, the xla and C paths on the same pair
    list, and the bare-kernel amortized dispatch cost. Same isolation
    rationale: self-budgeting script, subprocess timeout."""
    _FRAGMENT_COST = 180 if interpret else 300   # hard <=5 min cap
    if not _admit(_FRAGMENT_COST, "fragment_variants", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        cmd = [sys.executable,
               os.path.join(here, "scripts",
                            "bench_fragment_variants.py"),
               "--budget", str(_FRAGMENT_COST - 30)]
        if interpret:
            cmd.append("--interpret")
        proc = subprocess.run(
            cmd, capture_output=True, text=True,
            timeout=_FRAGMENT_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("FRAGMENT_JSON "):
                data = json.loads(line[len("FRAGMENT_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        if interpret:
            data["interpret"] = True
        stages["fragment_variants"] = data
    except Exception as e:  # noqa: BLE001
        errors.append(f"fragment_variants: {type(e).__name__}: {e}")


def _run_engine_rounds_stage(stages, errors):
    """Host-vs-device greedy-selection throughput on the e2e_1000 rung
    in a subprocess (scripts/bench_engine_rounds.py): the same planted-
    family workload run once with GALAH_TPU_GREEDY_STRATEGY=host and
    once with the round-based device fold, with a cluster-parity check
    and the round/conflict/fallback counters in the payload. Same
    isolation rationale as the variant matrices: self-budgeting script,
    subprocess timeout."""
    _ROUNDS_COST = 600
    if not _admit(_ROUNDS_COST, "engine_rounds", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(here, "scripts", "bench_engine_rounds.py"),
             "--budget", str(_ROUNDS_COST - 30)],
            capture_output=True, text=True,
            timeout=_ROUNDS_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("ENGINE_ROUNDS_JSON "):
                data = json.loads(line[len("ENGINE_ROUNDS_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        stages["engine_rounds"] = data
        # Flatten the verdict numbers (rates + round/conflict/fallback
        # counters) to scalar stages so _finalize_obs mirrors them into
        # run_report.json gauges alongside the ladder rungs.
        for k in ("device_genomes_per_sec", "host_genomes_per_sec",
                  "speedup"):
            if isinstance(data.get(k), (int, float)):
                stages[f"engine_rounds_{k}"] = data[k]
        for k, v in (data.get("counters") or {}).items():
            stages[f"engine_rounds_{k}"] = v
    except Exception as e:  # noqa: BLE001
        errors.append(f"engine_rounds: {type(e).__name__}: {e}")


def _run_e2e_overlap_stage(stages, errors):
    """Stage-serial vs fully overlapped dataflow on the e2e_1000 rung
    in a subprocess (scripts/bench_overlap.py): the same planted-
    family workload run once with GALAH_TPU_OVERLAP=0 (four sequential
    drains) and once with the fused sketch -> pair-screen ->
    speculative fragment-ANI -> eager greedy pipeline, with a cluster-
    parity check, the overlap counters, and the per-stage
    workload.pipeline_occupancy gauges in the payload. Same isolation
    rationale as the variant matrices: self-budgeting script,
    subprocess timeout."""
    _OVERLAP_COST = 600
    if not _admit(_OVERLAP_COST, "e2e_overlap", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(here, "scripts", "bench_overlap.py"),
             "--budget", str(_OVERLAP_COST - 30)],
            capture_output=True, text=True,
            timeout=_OVERLAP_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("OVERLAP_JSON "):
                data = json.loads(line[len("OVERLAP_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        stages["e2e_overlap"] = data
        # Flatten the verdict numbers (rates, speedup, occupancy) to
        # scalar stages so _finalize_obs mirrors them into
        # run_report.json gauges alongside the ladder rungs.
        one_core = isinstance(data.get("host_cores"), int) \
            and data["host_cores"] <= 1
        for k in ("overlapped_genomes_per_sec",
                  "serial_genomes_per_sec", "speedup", "host_cores"):
            # A 1-core host caps the overlap at ~1x by construction:
            # keep its speedup out of the flattened gauges so the
            # perf ledger never bands a capacity ceiling as a
            # regression (the nested payload still carries it).
            if k == "speedup" and one_core:
                continue
            if isinstance(data.get(k), (int, float)):
                stages[f"e2e_overlap_{k}"] = data[k]
        for stage_name, v in (data.get("occupancy") or {}).items():
            stages[f"e2e_overlap_occupancy_{stage_name}"] = v
        for k, v in (data.get("counters") or {}).items():
            stages[f"e2e_overlap_{k}"] = v
        # critical-path blame shares -> bench.flow_* gauges, so a
        # migrated bottleneck shows in the ledger like any perf drift
        flow = data.get("flow") or {}
        for stage_name, v in (flow.get("shares") or {}).items():
            if isinstance(v, (int, float)):
                stages[f"flow_{stage_name}_share"] = v
    except Exception as e:  # noqa: BLE001
        errors.append(f"e2e_overlap: {type(e).__name__}: {e}")


def _run_megakernel_stage(stages, errors):
    """Fused megakernel rounds vs per-window dense folds on the e2e
    rung in a subprocess (scripts/bench_megakernel.py): the same
    overlapped workload run with GALAH_TPU_MEGAKERNEL=1 and =0, with a
    cluster-parity check, the off/mega greedy-select dispatch ratio
    (gate >= 4x), and the critical path's host-blame share for the
    megakernel run — the gauge the fused rounds exist to drive down.
    Same isolation rationale as the variant matrices: self-budgeting
    script, subprocess timeout."""
    _MEGA_COST = 900
    if not _admit(_MEGA_COST, "megakernel", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(here, "scripts", "bench_megakernel.py"),
             "--budget", str(_MEGA_COST - 30)],
            capture_output=True, text=True,
            timeout=_MEGA_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("MEGAKERNEL_JSON "):
                data = json.loads(line[len("MEGAKERNEL_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        stages["megakernel"] = data
        # Flatten the verdict numbers to scalar stages so
        # _finalize_obs mirrors them into run_report.json gauges
        # alongside the ladder rungs.
        one_core = isinstance(data.get("host_cores"), int) \
            and data["host_cores"] <= 1
        for k in ("mega_genomes_per_sec", "off_genomes_per_sec",
                  "speedup", "dispatch_ratio", "host_share",
                  "host_blame_s", "host_cores"):
            # Same capacity-ceiling discipline as e2e_overlap: a
            # 1-core host caps the wall-clock speedup at ~1x by
            # construction, so keep it out of the flattened gauges
            # (the nested payload still carries it). dispatch_ratio
            # and host_share stay in — they measure structure, not
            # spare-core throughput.
            if k == "speedup" and one_core:
                continue
            if isinstance(data.get(k), (int, float)) \
                    and not isinstance(data.get(k), bool):
                stages[f"megakernel_{k}"] = data[k]
        for k, v in (data.get("counters") or {}).items():
            stages[f"megakernel_{k}"] = v
    except Exception as e:  # noqa: BLE001
        errors.append(f"megakernel: {type(e).__name__}: {e}")


def _run_allpairs_scale_stage(stages, errors):
    """1-D vs 2D tiled mesh all-pairs scaling in a subprocess
    (scripts/bench_allpairs_scale.py): candidate pairs/s and the
    modeled mesh.dcn_bytes_per_row for both mesh geometries at
    N in {1k, 5k, 20k} synthetic sketch rungs (pair-set parity
    gated), plus the cardinality-band prefilter's pruned fraction.
    Same isolation rationale as the variant matrices: self-budgeting
    script, subprocess timeout."""
    _ALLPAIRS_COST = 600
    if not _admit(_ALLPAIRS_COST, "allpairs_scale", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(here, "scripts", "bench_allpairs_scale.py"),
             "--budget", str(_ALLPAIRS_COST - 30)],
            capture_output=True, text=True,
            timeout=_ALLPAIRS_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("ALLPAIRS_JSON "):
                data = json.loads(line[len("ALLPAIRS_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        stages["allpairs_scale"] = data
        # Flatten the per-rung verdict numbers to scalar stages so
        # _finalize_obs mirrors them into run_report.json gauges and
        # the perf ledger gates DCN-ratio / speedup / pruning drift.
        for rung in data.get("rungs") or []:
            n = rung.get("n")
            for k in ("1d_pairs_per_sec", "2d_pairs_per_sec",
                      "speedup_2d", "dcn_ratio",
                      "bucket_pruned_fraction"):
                if isinstance(rung.get(k), (int, float)):
                    stages[f"allpairs_n{n}_{k}"] = rung[k]
    except Exception as e:  # noqa: BLE001
        errors.append(f"allpairs_scale: {type(e).__name__}: {e}")


def _run_ingest_variants_stage(stages, errors):
    """Storage-bound ingest->sketch matrix in a subprocess
    (scripts/bench_ingest.py --variants): end-to-end Mbp/s by
    strategy x workers x gzip over a >= 1 Gbp multi-file corpus,
    against the serial-prologue baseline (read everything, then one
    batched sketch pass — the pre-streaming pipeline shape), with the
    host/device cost split. The headline scalars are flattened into
    stages so _finalize_obs mirrors them into bench.* gauges and the
    perf ledger gates ingest-rate regressions. Same isolation
    rationale as the other matrices: self-budgeting script,
    subprocess timeout; the corpus is CPU-pinned host work either
    way."""
    _INGEST_COST = 420
    if not _admit(_INGEST_COST, "ingest_variants", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(here, "scripts", "bench_ingest.py"),
             "--variants", "--budget", str(_INGEST_COST - 90)],
            capture_output=True, text=True,
            timeout=_INGEST_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("INGEST_JSON "):
                data = json.loads(line[len("INGEST_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        stages["ingest_variants"] = data
        for k in ("overlapped_mbp_s", "serial_prologue_mbp_s",
                  "speedup_vs_serial"):
            if isinstance(data.get(k), (int, float)):
                stages[f"ingest_{k}"] = data[k]
    except Exception as e:  # noqa: BLE001
        errors.append(f"ingest_variants: {type(e).__name__}: {e}")


def _run_ingest_tiered_stage(stages, errors):
    """Out-of-core sketch tier vs all-resident in a subprocess
    (scripts/bench_ingest_tiered.py): peak-RSS delta and ingest rate
    at N in {1k, 20k, 100k} synthetic genomes, paged band walk vs the
    resident matrix, pair-dict parity gated per rung. The headline
    ``pagestore_*`` scalars flatten into stages so _finalize_obs
    mirrors them into bench.pagestore_* gauges and the perf ledger
    gates the RSS bound (paged/resident delta ratio; the tentpole's
    acceptance is <= 1/8 at the 100k rung) and the paged ingest rate.
    Self-budgeting script, subprocess timeout, host-side work — as
    real on the cpu-fallback branch as on the device one."""
    _TIERED_COST = 480
    if not _admit(_TIERED_COST, "ingest_tiered", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(here, "scripts", "bench_ingest_tiered.py"),
             "--budget", str(_TIERED_COST - 60)],
            capture_output=True, text=True,
            timeout=_TIERED_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("TIERED_JSON "):
                data = json.loads(line[len("TIERED_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        stages["ingest_tiered"] = data
        for k in ("pagestore_delta_rss_ratio",
                  "pagestore_paged_genomes_per_sec",
                  "pagestore_resident_genomes_per_sec",
                  "pagestore_page_ins", "pagestore_page_outs",
                  "pagestore_parity_ok"):
            if isinstance(data.get(k), (int, float)):
                stages[k] = data[k]
        if not data.get("parity_ok", False):
            errors.append("ingest_tiered: paged pair dict diverged "
                          "from the all-resident pass")
    except Exception as e:  # noqa: BLE001
        errors.append(f"ingest_tiered: {type(e).__name__}: {e}")


def _run_index_stage(stages, errors):
    """Incremental-index service numbers in a subprocess
    (scripts/bench_index.py): build the persistent index once over
    90% of a planted-family corpus, then time the two operations the
    subsystem exists for — insert of the remaining 10% (genomes/s,
    plus the sketch.minhash_computed delta proving only the new
    genomes were resketched) and the warm per-genome query sweep
    (p50/p95 ms; acceptance is warm p50 < 50 ms on CPU). Headline
    scalars flatten into stages so _finalize_obs mirrors them into
    bench.* gauges; workload.index_* gauges fingerprint the corpus so
    the perf ledger only compares like-sized index runs."""
    _INDEX_COST = 240
    if not _admit(_INDEX_COST, "index_service", errors):
        return
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(here, "scripts", "bench_index.py"),
             "--budget", str(_INDEX_COST - 60)],
            capture_output=True, text=True,
            timeout=_INDEX_COST, cwd=here)
        data = None
        for line in proc.stdout.splitlines():
            if line.startswith("INDEX_JSON "):
                data = json.loads(line[len("INDEX_JSON "):])
        if data is None:
            raise RuntimeError(
                f"rc={proc.returncode}: {proc.stderr[-400:]}")
        stages["index_service"] = data
        for k in ("build_genomes_per_sec", "insert_genomes_per_sec",
                  "insert_resketched", "query_p50_ms", "query_p95_ms"):
            if isinstance(data.get(k), (int, float)):
                stages[f"index_{k}"] = data[k]
        from galah_tpu import obs

        for k, hlp in (("n_genomes", "Index bench corpus size"),
                       ("n_insert", "Index bench insert-slice size")):
            if isinstance(data.get(k), (int, float)):
                obs.metrics.gauge(
                    f"workload.index_{k}", help=hlp).set(float(data[k]))
    except Exception as e:  # noqa: BLE001
        errors.append(f"index_service: {type(e).__name__}: {e}")


def _run_fleet_stage(stages, errors):
    """Elastic-fleet supervisor scaling (galah_tpu/fleet/): the same
    planted-family corpus through `galah-tpu fleet run` — 3 shards
    across 2 preemptible worker subprocesses plus the cross-shard
    merge — vs ONE single-process `cluster` run. Emits fleet
    genomes/s, the fleet/single wall ratio (worker-interpreter spinup
    + supervision + merge overhead), and the merge wall clock, and
    asserts the byte-identity contract on the way: a throughput
    number for a divergent answer is not a number."""
    _FLEET_COST = 480
    if not _admit(_FLEET_COST, "fleet_scale", errors):
        return
    import shutil
    import tempfile

    try:
        here = os.path.dirname(os.path.abspath(__file__))
        work = tempfile.mkdtemp(prefix="galah_fleetbench_")
        try:
            gdir = os.path.join(work, "genomes")
            os.makedirs(gdir, exist_ok=True)
            paths = _synth_families(n_genomes=24, genome_len=40_000,
                                    n_families=6, mut=0.03, seed=13,
                                    outdir=gdir)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            # shared profile cache: shard profiling warms what the
            # merge's cross-shard pass reuses, like a real deployment
            env["GALAH_TPU_CACHE"] = os.path.join(work, "cache")
            base = [sys.executable, "-m", "galah_tpu.cli"]
            common = ["--genome-fasta-files", *paths,
                      "--precluster-method", "skani",
                      "--cluster-method", "skani"]
            single_tsv = os.path.join(work, "single.tsv")
            t0 = time.perf_counter()
            proc = subprocess.run(
                base + ["cluster", "--platform", "cpu", *common,
                        "--output-cluster-definition", single_tsv],
                capture_output=True, text=True,
                timeout=_FLEET_COST // 2, cwd=here, env=env)
            single_s = time.perf_counter() - t0
            if proc.returncode != 0:
                raise RuntimeError(f"single-process run rc="
                                   f"{proc.returncode}: "
                                   f"{proc.stderr[-300:]}")
            fleet_tsv = os.path.join(work, "fleet.tsv")
            report = os.path.join(work, "fleet_report.json")
            t0 = time.perf_counter()
            proc = subprocess.run(
                base + ["fleet", "--platform", "cpu", "run", *common,
                        "--fleet-dir", os.path.join(work, "fleet"),
                        "--workers", "2", "--shards", "3",
                        "--output-cluster-definition", fleet_tsv,
                        "--run-report", report],
                capture_output=True, text=True,
                timeout=_FLEET_COST // 2, cwd=here, env=env)
            fleet_s = time.perf_counter() - t0
            if proc.returncode != 0:
                raise RuntimeError(f"fleet run rc={proc.returncode}: "
                                   f"{proc.stderr[-300:]}")
            with open(single_tsv, "rb") as f:
                single_bytes = f.read()
            with open(fleet_tsv, "rb") as f:
                if f.read() != single_bytes:
                    raise RuntimeError(
                        "fleet clusters differ from the "
                        "single-process run")
            stages["fleet_genomes_per_sec"] = round(
                len(paths) / fleet_s, 2)
            stages["fleet_vs_single_wall"] = round(fleet_s / single_s,
                                                   2)
            with open(report) as f:
                rep = json.load(f)
            fl = rep.get("fleet") or {}
            if isinstance(fl.get("merge_wall_s"), (int, float)):
                stages["fleet_merge_wall_s"] = round(
                    fl["merge_wall_s"], 3)
            from galah_tpu import obs

            for k, hlp in (("n_shards", "Fleet bench shard count"),
                           ("workers", "Fleet bench worker cap")):
                if isinstance(fl.get(k), (int, float)):
                    obs.metrics.gauge(
                        f"workload.fleet_{k}",
                        help=hlp).set(float(fl[k]))
            # Fleet critical path (v9 fleet_rollup): flatten the blame
            # decomposition into bench gauges so the driver artifact —
            # and the report --diff between sessions — carries where
            # the fleet wall went (scheduler vs compute vs straggler
            # wait vs merge), not just its total.
            ru = rep.get("fleet_rollup") or {}
            if isinstance(ru.get("fleet_wall_s"), (int, float)):
                obs.metrics.gauge(
                    "bench.fleet_wall_s",
                    unit="s", help="Fleet bench wall from the rollup"
                ).set(float(ru["fleet_wall_s"]))
            for comp, c in sorted((ru.get("components") or {}).items()):
                if not isinstance(c, dict):
                    continue
                if isinstance(c.get("blame_s"), (int, float)):
                    obs.metrics.gauge(
                        f"bench.fleet_{comp}_blame_s", unit="s",
                        help=f"Fleet wall blamed on {comp}"
                    ).set(float(c["blame_s"]))
                if isinstance(c.get("share"), (int, float)):
                    obs.metrics.gauge(
                        f"bench.fleet_{comp}_share",
                        help=f"Share of the fleet wall blamed on "
                             f"{comp}").set(float(c["share"]))
            if ru.get("bottleneck"):
                stages["fleet_bottleneck"] = ru["bottleneck"]
        finally:
            shutil.rmtree(work, ignore_errors=True)
    except Exception as e:  # noqa: BLE001
        errors.append(f"fleet_scale: {type(e).__name__}: {e}")


def run_ladder_stages(stages, errors):
    """North-star-relevant e2e evidence in the driver artifact itself.

    Two rungs, each with a sibling `_workload` key stating exactly what
    the number means (the workload shape changes the number more than
    the code does, so the artifact must say what was run):

      * e2e_1000_genomes_per_sec — cluster() on 1000 synthetic genomes
        with planted family structure (250 families x 4 members, 3%
        mutation, 100 kbp) at the DEFAULT config (murmur3 hashes,
        finch-style precluster + skani-style cluster). The BASELINE.md
        ladder's rung-2 class at N=1000, inside the driver artifact.
      * mega_256_genomes_per_sec — the dense-similarity worst case the
        reference advertises ("many closely related genomes >95% ANI",
        reference: README.md:18-26): ONE planted family of 256, every
        pair ~96% ANI, through the default skani+skani path. Nothing
        screens out; the exact-ANI stage does all-pairs work.

    Runs on whatever backend the caller already initialized (device or
    pinned CPU) — the JSON's `backend` field disambiguates.
    """
    from galah_tpu.api import generate_galah_clusterer

    def run_one(key, paths, values, workload):
        t0 = time.perf_counter()
        clusterer = generate_galah_clusterer(paths, values)
        clusters = clusterer.cluster()
        dt = time.perf_counter() - t0
        stages[key + "_genomes_per_sec"] = round(len(paths) / dt, 2)
        stages[key + "_n_clusters"] = len(clusters)
        stages[key + "_workload"] = workload

    base = {"ani": 95.0, "precluster_ani": 90.0,
            "min_aligned_fraction": 15.0, "fragment_length": 3000,
            "precluster_method": "finch", "cluster_method": "skani",
            "threads": 1}
    wd = _admitted_watchdog(900, "e2e_1000", errors)
    if wd:
        try:
            with wd:
                paths = _synth_families(
                    n_genomes=1000, genome_len=100_000,
                    n_families=250, mut=0.03, seed=11)
                run_one("e2e_1000", paths, dict(base),
                        "1000 synthetic genomes, 250 planted families "
                        "x4, 3% mutation, 100 kbp, default murmur3 "
                        "finch+skani")
        except Exception as e:  # noqa: BLE001
            errors.append(f"e2e_1000: {type(e).__name__}: {e}")
    wd = _admitted_watchdog(900, "mega_256", errors)
    if not wd:
        return
    try:
        with wd:
            paths = _synth_families(n_genomes=256, genome_len=100_000,
                                    n_families=1, mut=0.02, seed=11)
            mega = dict(base)
            mega.update(precluster_method="skani",
                        cluster_method="skani")
            run_one("mega_256", paths, mega,
                    "dense worst case: ONE planted family of 256, "
                    "every pair ~96% ANI, 100 kbp, default skani+skani "
                    "(nothing screens out)")
    except Exception as e:  # noqa: BLE001
        errors.append(f"mega_256: {type(e).__name__}: {e}")


def _finalize_obs(result, started_at):
    """Mirror the bench line into the metrics registry and, when
    GALAH_OBS_REPORT is set, write the same end-of-run run_report.json
    a cluster run produces (docs/observability.md) — so bench numbers
    are diffable with `galah-tpu report --diff` across captures.
    Telemetry must never lose the bench line: failures append to the
    errors field instead of raising."""
    try:
        from galah_tpu import obs
        from galah_tpu.config import env_value

        obs.metrics.gauge(
            "bench." + result["metric"],
            help="Headline bench metric",
            unit=result.get("unit", "")).set(result["value"])
        # Workload fingerprint gauges: the perf ledger keys cross-run
        # comparison on (N, K), so bench history only compares like
        # workloads (obs/ledger.py workload_fingerprint).
        obs.metrics.gauge(
            "workload.n_genomes",
            help="Bench production workload size").set(
            float(result.get("n_genomes", PRODUCTION_N)))
        obs.metrics.gauge(
            "workload.sketch_k",
            help="Bench sketch size").set(float(SKETCH_SIZE))
        if result.get("vs_baseline") is not None:
            obs.metrics.gauge(
                "bench.vs_baseline",
                help="Headline metric over the CPU stand-in "
                     "baseline").set(result["vs_baseline"])
        for name, val in result.get("stages", {}).items():
            if isinstance(val, (int, float)) and not isinstance(
                    val, bool):
                obs.metrics.gauge(f"bench.{name}").set(val)
        obs.metrics.counter(
            "bench.errors",
            help="Bench stages that failed").inc(
            len(result.get("errors", [])))
        report_path = env_value("GALAH_OBS_REPORT") or None
        obs.finalize("bench", report_path=report_path,
                     started_at=started_at)
    except Exception as e:  # noqa: BLE001
        result.setdefault("errors", []).append(
            f"obs: {type(e).__name__}: {e}")


def main():
    started_at = time.time()
    result = {
        "metric": "production_pairwise_genome_pairs_per_sec",
        "value": 0.0,
        "unit": "pairs/s",
        "vs_baseline": None,
        "baseline": "strongest of xla-cpu-multicore tile_stats and the "
                    "compiled-C dense merged walk (csrc/pairstats.c) "
                    "over the same all-pairs workload — the stand-ins "
                    "for the reference's compiled dense pair loop "
                    "(src/finch.rs:53-73; no rustc in image). The "
                    "headline is the AUTO production path (host "
                    "collision screen + batched device survivors) on "
                    "family-structured sketches; stages record the "
                    "dense Mosaic kernel apples-to-apples against the "
                    "dense baselines AND this framework's own screened "
                    "CPU path (cpu_production_pairs_per_sec) so the "
                    "tunnel-handicap comparison is on the record.",
        "stages": {},
        "errors": [],
    }
    stages = result["stages"]
    errors = result["errors"]

    # 1. CPU baselines in subprocesses (never touch the TPU tunnel):
    # the XLA-CPU tiled pass AND the compiled-C merged-bottom-k walk
    # (csrc/pairstats.c, the closest stand-in for the reference's
    # compiled Rust loop). The stronger one becomes the baseline.
    cpu_pps = None
    try:
        xla_pps = run_sub(_CPU_BASELINE_CODE % (SKETCH_SIZE, K),
                          timeout=300)
        stages["cpu_xla_baseline_pairs_per_sec"] = round(xla_pps, 1)
        cpu_pps = xla_pps
    except Exception as e:  # noqa: BLE001
        errors.append(f"cpu_baseline: {type(e).__name__}: {e}")
    try:
        c_pps = run_sub(_C_BASELINE_CODE % (SKETCH_SIZE, K),
                        timeout=300)
        stages["cpu_c_baseline_pairs_per_sec"] = round(c_pps, 1)
        cpu_pps = max(cpu_pps or 0.0, c_pps)
    except Exception as e:  # noqa: BLE001
        errors.append(f"c_baseline: {type(e).__name__}: {e}")
    if cpu_pps:
        stages["cpu_baseline_pairs_per_sec"] = round(cpu_pps, 1)
    # This framework's own screened CPU path on the headline workload —
    # not the vs_baseline denominator (that is the reference stand-in),
    # but required for an honest single-chip-vs-this-box comparison.
    try:
        cpu_prod = run_sub(_CPU_PRODUCTION_CODE, timeout=300)
        stages["cpu_production_pairs_per_sec"] = round(cpu_prod, 1)
    except Exception as e:  # noqa: BLE001
        errors.append(f"cpu_production: {type(e).__name__}: {e}")

    # 2. Bounded-timeout probe of the device backend, one retry.
    ok, reason, detail = probe_backend()
    if not ok:
        # TPU unreachable: report the honest CPU measurement instead of
        # a dead zero — the line stays parseable. The errors entry is a
        # pure key=value token line (reason is a single token, e.g.
        # `probe-timeout`); the longer human text goes only to the
        # structured backend_reason_detail field.
        errors.append(f"backend=cpu-fallback reason={reason}")
        result["backend"] = "cpu-fallback"
        result["backend_reason"] = reason
        result["backend_reason_detail"] = detail
        cpu_prod = stages.get("cpu_production_pairs_per_sec")
        if cpu_prod:
            result["value"] = cpu_prod
            if cpu_pps:
                result["vs_baseline"] = round(cpu_prod / cpu_pps, 2)
        elif cpu_pps:
            result["value"] = round(cpu_pps, 1)
            result["vs_baseline"] = 1.0
        # End-to-end evidence even without a device: pin the platform
        # to cpu BEFORE any jax use (a plain import in this process
        # would attach to the wedged tunnel the probe just timed out
        # on) and run the fast-mode cluster() stage.
        try:
            with watchdog(240):
                import jax

                jax.config.update("jax_platforms", "cpu")
                gps, nc, _ = bench_e2e(fast=True)
                stages["e2e_fast_genomes_per_sec"] = round(gps, 2)
                stages["e2e_fast_n_clusters"] = nc
        except Exception as e:  # noqa: BLE001
            errors.append(f"e2e-fallback: {type(e).__name__}: {e}")
        # Pin the platform UNCONDITIONALLY before the ladder stages:
        # if the watchdog fired above, the jax.config update may never
        # have happened, and the ladder's first jax import would attach
        # to the same wedged tunnel the probe timed out on. The env var
        # covers both this process (if jax is not yet imported) and the
        # config path (if it is).
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception as e:  # noqa: BLE001
            errors.append(f"cpu-pin: {type(e).__name__}: {e}")
        run_ladder_stages(stages, errors)
        _run_engine_rounds_stage(stages, errors)
        # The overlapped-dataflow comparison is as real on the
        # cpu-fallback branch as on the device one (the occupancy
        # split documents how much of the win a 1-core host caps).
        _run_e2e_overlap_stage(stages, errors)
        # The fused-rounds comparison is structural (dispatch ratio,
        # host-blame share, parity) so it is as real on the fallback
        # branch; only the wall-clock speedup is capacity-capped.
        _run_megakernel_stage(stages, errors)
        # The 1-D vs 2D mesh comparison runs the same XLA tiles on
        # the 8-device CPU sim — the DCN model and parity gate are as
        # real here as on hardware.
        _run_allpairs_scale_stage(stages, errors)
        # Strategy matrix still recorded (interpret mode) so a
        # no-tunnel capture is a documented negative, not a silence.
        _run_pairlist_variants_stage(stages, errors, interpret=True)
        _run_fragment_variants_stage(stages, errors, interpret=True)
        # Ingest->sketch is host-side work: the matrix is as real on
        # the cpu-fallback branch as on the device one.
        _run_ingest_variants_stage(stages, errors)
        # The memory-tier comparison is pure host/RSS measurement.
        _run_ingest_tiered_stage(stages, errors)
        # The index service is specified against CPU latency targets,
        # so the fallback branch runs the real measurement too.
        _run_index_stage(stages, errors)
        # Fleet workers are subprocesses either way — the supervision
        # overhead measurement is as real on the fallback branch.
        _run_fleet_stage(stages, errors)
        _finalize_obs(result, started_at)
        print(json.dumps(result))
        return

    try:
        import jax

        result["backend"] = jax.default_backend()
        result["n_devices"] = jax.device_count()
    except Exception as e:  # noqa: BLE001
        errors.append(f"backend init: {type(e).__name__}: {e}")
        _finalize_obs(result, started_at)
        print(json.dumps(result))
        return

    # 3. Headline: the AUTO production pairwise path (host collision
    # screen + batched Mosaic pairlist survivors on device) on
    # family-structured sketches — what a reference user switching to
    # this framework actually runs above the sparse crossover. The
    # vs_baseline denominator is the reference-style dense compiled
    # loop on the same per-pair work (bit-identical surviving pairs).
    try:
        with watchdog(300):
            result["value"] = round(bench_production(), 1)
            result["n_genomes"] = PRODUCTION_N
            if cpu_pps:
                result["vs_baseline"] = round(result["value"] / cpu_pps, 2)
    except Exception as e:  # noqa: BLE001
        errors.append(f"production_sparse: {type(e).__name__}: {e}")

    # 3b. The dense Mosaic pair-stats kernel at a size fit to the
    # budget — apples-to-apples against the dense CPU baselines.
    try:
        with watchdog(300):
            env_n = os.environ.get("GALAH_BENCH_N")
            n = int(env_n) if env_n else pick_n()
            stages["dense_kernel_n_genomes"] = n
            mat = _sketches(n, SKETCH_SIZE, seed=0)
            stages["dense_kernel_pairs_per_sec"] = round(
                bench_extraction(mat), 1)
    except Exception as e:  # noqa: BLE001
        errors.append(
            f"pairwise_pallas: {type(e).__name__}: {e}")

    # 4. The XLA searchsorted path on a smaller tile, for the record.
    try:
        with watchdog(240):
            mat = _sketches(512, SKETCH_SIZE, seed=0)
            stages["pairwise_xla_pairs_per_sec"] = round(
                bench_extraction(mat, repeats=1, use_pallas=False), 1)
    except Exception as e:  # noqa: BLE001
        errors.append(f"pairwise_xla: {type(e).__name__}: {e}")

    # 4b. North-star ladder rungs (N=1000 e2e + dense mega regime) —
    # BEFORE the amortized/sketch stages so a tight budget drops the
    # redundant kernel detail, not the verdict-relevant evidence (the
    # amortized campaign also runs standalone in the watcher).
    run_ladder_stages(stages, errors)
    _run_engine_rounds_stage(stages, errors)

    # 4b'. Stage-serial vs fully overlapped dataflow on the same rung:
    # parity gate + genomes/s for both schedules, plus the per-stage
    # occupancy gauges that show where the pipeline sat busy.
    _run_e2e_overlap_stage(stages, errors)

    # 4b'a. Fused megakernel rounds vs per-window dense folds: parity
    # gate, off/mega dispatch ratio (>= 4x), and the critical path's
    # host-blame share — the megakernel's headline gauge.
    _run_megakernel_stage(stages, errors)

    # 4b''. 1-D vs 2D tiled mesh all-pairs scaling: pairs/s, the
    # modeled per-row DCN bytes for both geometries (the
    # communication-avoiding claim), and the cardinality-band
    # prefilter's pruned fraction, parity gated per rung.
    _run_allpairs_scale_stage(stages, errors)

    # 4c. Amortized ON-CHIP kernel throughput (device-resident inputs,
    # fori_loop repeats inside one dispatch): the MFU measurement that
    # separates kernel speed from tunnel dispatch/transfer. Subprocess
    # so a wedge mid-campaign cannot take down the bench line.
    _AMORT_COST = 900
    if _admit(_AMORT_COST, "amortized", errors):
        try:
            here = os.path.dirname(os.path.abspath(__file__))
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(here, "scripts", "bench_amortized.py"),
                 "--fast"],
                capture_output=True, text=True, timeout=_AMORT_COST,
                cwd=here)
            amort = None
            for line in proc.stdout.splitlines():
                if line.startswith("AMORTIZED_JSON "):
                    amort = json.loads(line[len("AMORTIZED_JSON "):])
            if amort is None:
                raise RuntimeError(
                    f"rc={proc.returncode}: {proc.stderr[-400:]}")
            stages["amortized_on_chip"] = amort
        except Exception as e:  # noqa: BLE001
            errors.append(f"amortized: {type(e).__name__}: {e}")

    # 4d. Pairlist strategy matrix: every survivor-evaluation strategy
    # (blocked P sweep, gather-dense, XLA) plus the per-term cost
    # breakdown (grid overhead, DMA floor, u64-emulation tax) that
    # turns a missed >=25%-of-ceiling target into a documented
    # negative. Self-budgeting inside the subprocess; hard 5 min cap.
    _run_pairlist_variants_stage(stages, errors)

    # 4e. Fragment-ANI strategy matrix: the exact-stage twin — pallas
    # pack sweep (launches per pair, job/span occupancy), xla and C
    # baselines, bare-kernel dispatch cost. Same subprocess isolation.
    _run_fragment_variants_stage(stages, errors)

    # 4f. Storage-bound ingest->sketch matrix: streamed pipeline vs
    # the serial-prologue baseline over a >= 1 Gbp corpus.
    _run_ingest_variants_stage(stages, errors)

    # 4f'. Out-of-core sketch tier vs all-resident: peak-RSS ratio
    # and ingest rate per rung, pair-dict parity gated.
    _run_ingest_tiered_stage(stages, errors)

    # 4g. Incremental-index service: build-once, insert-10%,
    # warm query-latency sweep (p50 target < 50 ms on CPU).
    _run_index_stage(stages, errors)

    # 4h. Elastic fleet: sharded multi-worker run vs single-process,
    # byte-identity asserted, supervision + merge overhead recorded.
    _run_fleet_stage(stages, errors)

    # 5. Sketching throughput on real FASTA bytes, both hash algos —
    # each with its own watchdog so one failure never loses the other.
    # 600 s: a cold tunnel session compiles every chunk-bucket variant
    # at 20-40 s each, which is what timed the round-3 capture out at
    # 240 s — the budget must cover compiles, not just compute.
    for algo, key in (("murmur3", "sketch_bp_per_sec"),
                      ("tpufast", "sketch_tpufast_bp_per_sec")):
        wd = _admitted_watchdog(600, f"sketching-{algo}", errors)
        if not wd:
            continue
        try:
            with wd:
                bps = bench_sketching(algo)
                if bps:
                    stages[key] = round(bps, 1)
        except Exception as e:  # noqa: BLE001
            errors.append(f"sketching-{algo}: {type(e).__name__}: {e}")
    wd = _admitted_watchdog(600, "sketching-batch", errors)
    if wd:
        try:
            with wd:
                bps = bench_sketching_batch("murmur3")
                if bps:
                    stages["sketch_batch_bp_per_sec"] = round(bps, 1)
        except Exception as e:  # noqa: BLE001
            errors.append(f"sketching-batch: {type(e).__name__}: {e}")

    # 6. End-to-end cluster() on planted families, default and fast
    # mode (each with its own watchdog).
    paths = None
    wd = _admitted_watchdog(300, "e2e", errors)
    if wd:
        try:
            with wd:
                gps, n_clusters, paths = bench_e2e()
                stages["e2e_genomes_per_sec"] = round(gps, 2)
                stages["e2e_n_clusters"] = n_clusters
        except Exception as e:  # noqa: BLE001
            errors.append(f"e2e: {type(e).__name__}: {e}")
    wd = _admitted_watchdog(300, "e2e-fast", errors)
    if wd:
        try:
            with wd:
                gps, n_clusters, _ = bench_e2e(fast=True, paths=paths)
                stages["e2e_fast_genomes_per_sec"] = round(gps, 2)
                stages["e2e_fast_n_clusters"] = n_clusters
        except Exception as e:  # noqa: BLE001
            errors.append(f"e2e-fast: {type(e).__name__}: {e}")

    _finalize_obs(result, started_at)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
