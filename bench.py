"""Benchmark: all-pairs MinHash ANI throughput (genome-pairs/sec).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured op is the framework's hot path — the on-device all-pairs
sketch comparison replacing the reference's host O(N^2) pair loop
(reference: src/finch.rs:53-73). The whole N x N pass (pair stats,
threshold, upper-triangle mask, count reduction) runs as ONE sharded
device program (parallel/mesh.py: sharded_pair_count), so the number
reflects device throughput rather than dispatch latency. `vs_baseline`
is the speedup over the same merged-bottom-k computation single-threaded
on the host (numpy) — the stand-in for the reference's CPU path (the
reference publishes no numbers; see BASELINE.md).
"""

import json
import time

import numpy as np


def _sketches(n, sketch_size, seed):
    rng = np.random.default_rng(seed)
    mat = rng.integers(0, 1 << 63, size=(n, sketch_size), dtype=np.uint64)
    mat.sort(axis=1)
    return mat


def bench_device(mat, k, min_ani=0.95, col_tile=256, repeats=3):
    from galah_tpu.parallel import make_mesh, sharded_pair_count

    mesh = make_mesh()
    n = mat.shape[0]
    sharded_pair_count(mat, k=k, min_ani=min_ani, mesh=mesh,
                       col_tile=col_tile)  # warmup + compile
    t0 = time.perf_counter()
    for _ in range(repeats):
        count = sharded_pair_count(mat, k=k, min_ani=min_ani, mesh=mesh,
                                   col_tile=col_tile)
    dt = (time.perf_counter() - t0) / repeats
    assert count >= 0
    return (n * n) / dt


def pick_n(k, sketch_size, budget_s=20.0, n_max=8192):
    """Calibrate: time a small single-dispatch pass, then choose the
    largest n whose measured-rate runtime fits the budget. Keeps the
    benchmark meaningful on fast hardware without ever blowing the
    driver's timeout on slow paths."""
    n0 = 256
    mat = _sketches(n0, sketch_size, seed=9)
    rate = bench_device(mat, k, repeats=1)
    n = n0
    while n < n_max and (2 * n) ** 2 / rate < budget_s:
        n *= 2
    return n


def bench_host_numpy(mat, k, sketch_size, n_pairs=256):
    """Single-thread host merged-bottom-k Jaccard as the CPU baseline."""
    from galah_tpu.ops.minhash_np import MinHashSketch, mash_ani

    sketches = [MinHashSketch(hashes=row, sketch_size=sketch_size, kmer=k)
                for row in mat]
    pairs = [(i, (i * 7 + 1) % len(sketches)) for i in range(n_pairs)]
    t0 = time.perf_counter()
    for i, j in pairs:
        mash_ani(sketches[i], sketches[j])
    dt = time.perf_counter() - t0
    return len(pairs) / dt


def main():
    import os

    k = 21
    sketch_size = 1000
    env_n = os.environ.get("GALAH_BENCH_N")
    n = int(env_n) if env_n else pick_n(k, sketch_size)
    mat = _sketches(n, sketch_size, seed=0)

    device_pps = bench_device(mat, k)
    host_pps = bench_host_numpy(mat, k, sketch_size)

    print(json.dumps({
        "metric": "minhash_allpairs_genome_pairs_per_sec",
        "value": round(device_pps, 1),
        "unit": "pairs/s",
        "vs_baseline": round(device_pps / host_pps, 2),
    }))


if __name__ == "__main__":
    main()
