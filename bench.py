"""Benchmark: tiled all-pairs MinHash ANI throughput (genome-pairs/sec).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured op is the framework's hot path — the device kernel replacing
the reference's host O(N^2) sketch-compare loop (reference:
src/finch.rs:53-73). `vs_baseline` is the speedup over the same
merged-bottom-k computation run single-threaded on the host (numpy), the
stand-in for the reference's CPU path (the reference publishes no numbers;
see BASELINE.md).
"""

import json
import time

import numpy as np


def _sketches(n, sketch_size, seed):
    rng = np.random.default_rng(seed)
    mat = rng.integers(0, 1 << 63, size=(n, sketch_size), dtype=np.uint64)
    mat.sort(axis=1)
    return mat


def bench_device(mat, k, sketch_size, row_tile=256, col_tile=256):
    import jax
    import jax.numpy as jnp

    from galah_tpu.ops.pairwise import tile_ani

    n = mat.shape[0]
    jmat = jax.device_put(jnp.asarray(mat))

    def run():
        acc = 0.0
        for r0 in range(0, n, row_tile):
            rows = jax.lax.dynamic_slice_in_dim(jmat, r0, row_tile, 0)
            for c0 in range(0, n, col_tile):
                cols = jax.lax.dynamic_slice_in_dim(jmat, c0, col_tile, 0)
                t = tile_ani(rows, cols, sketch_size, k)
                acc += float(t[0, 0])  # force materialization
        return acc

    run()  # warmup + compile
    t0 = time.perf_counter()
    run()
    dt = time.perf_counter() - t0
    return (n * n) / dt


def bench_host_numpy(mat, k, sketch_size, n_pairs=512):
    """Single-thread host merged-bottom-k Jaccard as the CPU baseline."""
    from galah_tpu.ops.minhash_np import MinHashSketch, mash_ani

    sketches = [MinHashSketch(hashes=row, sketch_size=sketch_size, kmer=k)
                for row in mat]
    pairs = [(i, (i * 7 + 1) % len(sketches)) for i in range(n_pairs)]
    t0 = time.perf_counter()
    for i, j in pairs:
        mash_ani(sketches[i], sketches[j])
    dt = time.perf_counter() - t0
    return len(pairs) / dt


def main():
    k = 21
    sketch_size = 1000
    n = 2048
    mat = _sketches(n, sketch_size, seed=0)

    device_pps = bench_device(mat, k, sketch_size)
    host_pps = bench_host_numpy(mat, k, sketch_size)

    print(json.dumps({
        "metric": "minhash_allpairs_genome_pairs_per_sec",
        "value": round(device_pps, 1),
        "unit": "pairs/s",
        "vs_baseline": round(device_pps / host_pps, 2),
    }))


if __name__ == "__main__":
    main()
