#!/usr/bin/env python
"""Fleet observability gate (host CPU, no tunnel use).

One small sharded fleet run with the OpenMetrics textfile exporter and
a fast heartbeat enabled, then the three fleet-plane checks
(docs/observability.md "Fleet observability"):

  1. ``galah-tpu fleet analyze`` exits 0 on the completed fleet dir
     and its blame table conserves the fleet wall (components sum to
     fleet_wall_s within 1%) with a named bottleneck.
  2. ``galah-tpu top <fleet_dir> --json`` renders the per-shard grid.
  3. The ``.prom`` textfile the heartbeat exported parses under the
     Prometheus text-format parser and carries the fleet blame series.

Exits 0 on success, 1 on any failed check — the validation harness
wraps this in a soft-warn stage so a telemetry regression is reported
in the capture without discarding the remaining hardware stages.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from chaos_run import fleet_argv, make_workload  # noqa: E402


def fail(msg: str) -> None:
    print(f"fleet_observe: FAIL: {msg}")
    sys.exit(1)


def check_prom(path: str) -> None:
    if not os.path.exists(path):
        fail(f"exporter never wrote {path}")
    with open(path) as f:
        page = f.read()
    try:
        from prometheus_client.parser import text_string_to_metric_families
    except ImportError:
        # Degraded check: format shape only (the tests carry the real
        # parser gate; this host just lacks prometheus_client).
        if "# TYPE galah_fleet_wall_seconds gauge" not in page:
            fail("no galah_fleet_wall_seconds TYPE line in .prom")
        print("fleet_observe: prometheus_client absent — "
              "shape-checked .prom only")
        return
    fams = {f.name: f for f in text_string_to_metric_families(page)}
    for name in ("galah_fleet_wall_seconds", "galah_fleet_blame_seconds"):
        if name not in fams:
            fail(f"series {name} missing from {path} "
                 f"(got {sorted(fams)})")
    blame = {s.labels.get("component"): s.value
             for s in fams["galah_fleet_blame_seconds"].samples}
    print(f"fleet_observe: .prom parsed — {len(fams)} families, "
          f"blame components {sorted(k for k in blame if k)}")


def main() -> None:
    work = tempfile.mkdtemp(prefix="fleet_observe_")
    try:
        gdir = os.path.join(work, "genomes")
        os.makedirs(gdir)
        genomes = make_workload(gdir, seed=7, families=2, members=5,
                                length=12_000)
        fleet_dir = os.path.join(work, "fleet")
        out_tsv = os.path.join(work, "clusters.tsv")
        report = os.path.join(work, "report.json")
        prom = os.path.join(work, "galah.prom")
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "GALAH_OBS_OPENMETRICS": prom,
            "GALAH_OBS_HEARTBEAT_S": "0.5",
            "GALAH_TPU_FLEET_HEARTBEAT_S": "0.5",
        })
        proc = subprocess.run(
            fleet_argv(genomes, fleet_dir, out_tsv, report,
                       resume=False, shards=3),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=600)
        if proc.returncode != 0:
            print(proc.stdout.decode(errors="replace")[-3000:])
            fail(f"fleet run exited {proc.returncode}")

        # -- fleet analyze: blame table + conservation ----------------
        proc = subprocess.run(
            [sys.executable, "-m", "galah_tpu.cli", "fleet", "analyze",
             "--json", fleet_dir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            timeout=120)
        if proc.returncode != 0:
            print(proc.stderr.decode(errors="replace")[-2000:])
            fail(f"fleet analyze exited {proc.returncode}")
        ru = json.loads(proc.stdout)
        wall = ru["fleet_wall_s"]
        blame = sum(c["blame_s"] for c in ru["components"].values())
        if not wall or abs(blame - wall) > 0.01 * wall:
            fail(f"blame sum {blame:.3f}s vs wall {wall:.3f}s")
        print(f"fleet_observe: rollup conserves wall "
              f"({blame:.2f}s / {wall:.2f}s), bottleneck "
              f"{ru.get('bottleneck')!r}")
        subprocess.run(
            [sys.executable, "-m", "galah_tpu.cli", "fleet", "analyze",
             fleet_dir], timeout=120)  # human table into the capture

        # -- top --json fleet grid ------------------------------------
        proc = subprocess.run(
            [sys.executable, "-m", "galah_tpu.cli", "top", fleet_dir,
             "--json"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            timeout=120)
        if proc.returncode != 0:
            print(proc.stderr.decode(errors="replace")[-2000:])
            fail(f"top --json exited {proc.returncode}")
        grid = json.loads(proc.stdout)
        if not grid.get("shards"):
            fail("top --json fleet grid has no shards")
        print(f"fleet_observe: fleet grid shows "
              f"{len(grid['shards'])} shard(s)")

        # -- OpenMetrics textfile -------------------------------------
        check_prom(prom)
        print("fleet_observe: OK")
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
