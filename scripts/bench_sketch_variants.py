"""Measure the sketching-stage variants on the live TPU.

Run when the tunnel is healthy. Answers, with captured numbers:
  1. packed vs unpacked chunk upload (is the 2.7x byte cut visible?);
  2. hash-only vs hash+bottom-k fold (is the u64 sort the bottleneck?);
  3. per-genome vs grouped batch sketching on real MAGs (dispatch
     round-trip amortization).

Timings force host materialization — through the tunnel,
block_until_ready does not actually block.
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def _timeit(fn, repeats=3):
    fn()  # compile/warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    from galah_tpu.ops import hashing

    assert jax.default_backend() == "tpu", jax.default_backend()

    C = 1 << 21  # 2 Mi bases — one mid-size MAG chunk
    rng = np.random.default_rng(0)
    codes = rng.integers(0, 4, size=C).astype(np.uint8)
    offs = jnp.asarray(np.full(1, 2**31 - 1, dtype=np.int32))
    packed, ambits = hashing.pack_codes_host(codes)

    for algo in ("murmur3", "tpufast"):
        # materialize only 4 hashes: a full-array download would be a
        # constant ~16 MiB device->host cost swamping the upload delta
        t_unpacked = _timeit(lambda: np.asarray(
            hashing.canonical_kmer_hashes_chunk(
                jnp.asarray(codes), offs, jnp.int32(0), k=21,
                algo=algo)[:4]))
        t_packed = _timeit(lambda: np.asarray(
            hashing.canonical_kmer_hashes_chunk_packed(
                jnp.asarray(packed), jnp.asarray(ambits), offs,
                jnp.int32(0), k=21, algo=algo)[:4]))
        print(f"{algo}: unpacked {C / t_unpacked / 1e6:.1f} Mpos/s, "
              f"packed {C / t_packed / 1e6:.1f} Mpos/s "
              f"(upload {C} vs {C // 4 + C // 8} B)", flush=True)

    # hash+fold vs hash-only (device-resident input isolates compute)
    dev_packed = jax.device_put(jnp.asarray(packed))
    dev_ambits = jax.device_put(jnp.asarray(ambits))

    def hash_only():
        h = hashing.canonical_kmer_hashes_chunk_packed(
            dev_packed, dev_ambits, offs, jnp.int32(0), k=21)
        return np.asarray(h[:4])

    def hash_fold():
        h = hashing.canonical_kmer_hashes_chunk_packed(
            dev_packed, dev_ambits, offs, jnp.int32(0), k=21)
        running = jnp.full((1000,), hashing.HASH_SENTINEL)
        return np.asarray(hashing.bottom_k_update(running, h, 1000)[:4])

    t_h = _timeit(hash_only)
    t_hf = _timeit(hash_fold)
    print(f"hash-only {C / t_h / 1e6:.1f} Mpos/s, hash+bottom-k fold "
          f"{C / t_hf / 1e6:.1f} Mpos/s (sort overhead "
          f"{(t_hf - t_h) / t_hf * 100:.0f}%)", flush=True)

    # Mosaic murmur state machine (ops/pallas_sketch.py) vs the XLA
    # u64-emulated hash core, device-resident key words: answers
    # whether the 16-bit-limb kernel beats XLA's generic emulation
    # on-chip (parity is separately pinned in test_tpu_hw.py).
    from galah_tpu.ops.hashing import _murmur3_k21_1d
    from galah_tpu.ops.pallas_sketch import murmur3_k21_pallas

    n = C
    kw = [jax.device_put(jnp.asarray(
        rng.integers(0, 1 << 64, size=n, dtype=np.uint64)))
        for _ in range(3)]

    @jax.jit
    def xla_hash(k1, k2, t):
        # the same state machine on the same pre-assembled words, via
        # XLA's u64 emulation (byte re-extraction feeds the shared
        # assembly in _murmur3_k21_1d; shift/and cost is negligible
        # next to the 11 u64 multiplies being measured)
        cb = [(k1 >> jnp.uint64(8 * b)) & jnp.uint64(0xFF)
              for b in range(8)]
        cb += [(k2 >> jnp.uint64(8 * b)) & jnp.uint64(0xFF)
               for b in range(8)]
        cb += [(t >> jnp.uint64(8 * b)) & jnp.uint64(0xFF)
               for b in range(5)]
        return _murmur3_k21_1d(cb, 0)

    t_xla = _timeit(lambda: np.asarray(xla_hash(*kw)[:4]))
    t_mosaic = _timeit(lambda: np.asarray(
        murmur3_k21_pallas(*kw, seed=0)[:4]))
    print(f"murmur core: XLA {n / t_xla / 1e6:.1f} Mkmer/s, Mosaic "
          f"{n / t_mosaic / 1e6:.1f} Mkmer/s "
          f"({t_xla / t_mosaic:.2f}x)", flush=True)

    # per-genome vs batch on real MAGs (shared bench corpus)
    from bench import bench_genomes
    from galah_tpu.ops.minhash import (
        sketch_genome_device,
        sketch_genomes_device_batch,
    )

    genomes, total_bp = bench_genomes()
    t_single = _timeit(
        lambda: [sketch_genome_device(g) for g in genomes], repeats=2)
    t_batch = _timeit(
        lambda: sketch_genomes_device_batch(genomes), repeats=2)
    print(f"6 real MAGs ({total_bp / 1e6:.1f} Mbp): per-genome "
          f"{total_bp / t_single / 1e6:.1f} Mbp/s, batch "
          f"{total_bp / t_batch / 1e6:.1f} Mbp/s", flush=True)


if __name__ == "__main__":
    main()
