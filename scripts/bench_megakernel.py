"""Fused megakernel rounds vs per-window dense folds on the e2e rung.

The megakernel (ops/device_queue.py + ops/megakernel.py) collapses a
slab of consecutive greedy round windows into two device programs —
one pow2-bucketed enqueue of the slab's surviving pairs and one fused
fold — in place of one dense window fold per window plus the host
round-trips between them. This stage prices exactly that on the bench
ladder's e2e rung workload (planted families, 3% mutation, 100 kbp),
end to end through ``generate_galah_clusterer(...).cluster()``:

  * megakernel: GALAH_TPU_MEGAKERNEL=1 (pinned — a fused-fold failure
    must fail the stage, not silently price the dense fallback), run
    FIRST so its jit compiles land inside its own timing;
  * off: GALAH_TPU_MEGAKERNEL=0, the per-window dense-fold baseline;
  * both: GALAH_TPU_OVERLAP=1 + the xla/device twin pins of
    bench_overlap.py, rep-rounds=16 so a full slab fuses
    SLAB_WINDOWS(16) windows and the dispatch win is measurable.

Verdict numbers:

  * ``parity`` — identical clusterings (a failure zeroes the speedup:
    the megakernel is a scheduling change, not an algorithm change);
  * ``dispatch_ratio`` — greedy-select dispatches per run, off/mega;
    the acceptance gate is >= 4x (``dispatch_gate``);
  * ``host_share`` / ``host_blame_s`` — the critical path's host-vs-
    device blame decomposition for the megakernel run
    (obs/flow.critical_path), THE headline gauge: the megakernel
    exists to drive host orchestration share down (<10% on the
    1000-genome rung once device math dominates; on a 1-core CPU host
    both sides share one core, so read it with `host_cores`).

Self-budgeting like the variant matrices: under a tight --budget the
workload downshifts to a 200-genome rung (recorded in `workload`), and
a partial run still prints MEGAKERNEL_JSON with what it measured.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_T0 = time.monotonic()

# Megakernel bookkeeping copied into the payload (deltas across the
# timed megakernel run).
_COUNTERS = ("megakernel-slab-folds", "megakernel-overflow-spills",
             "megakernel-demoted", "greedy-select-dispatches",
             "greedy-rounds", "overlap-eager-rounds",
             "greedy-host-fallback-windows")

_VALUES = {"ani": 95.0, "precluster_ani": 90.0,
           "min_aligned_fraction": 15.0, "fragment_length": 3000,
           "precluster_method": "finch", "cluster_method": "skani",
           "threads": 1, "rep_rounds": 16}

# Pinned for BOTH runs — the comparison isolates the megakernel, so
# everything else (sketcher, greedy strategy, overlap) stays a twin.
_PINS = {"GALAH_TPU_SKETCH_STRATEGY": "xla",
         "GALAH_TPU_GREEDY_STRATEGY": "device",
         "GALAH_TPU_OVERLAP": "1",
         # a 16-window slab of 16-genome windows inside a 100-genome
         # family carries ~13k materialized edges; the default 4096
         # cap would spill every slab and price the dense path
         "GALAH_TPU_QUEUE_CAP": "16384"}


def _left(budget):
    return budget - (time.monotonic() - _T0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=None,
                    help="seconds for the whole stage (default 570, "
                         "capped by GALAH_BENCH_STAGE_CAP)")
    args = ap.parse_args()

    budget = args.budget if args.budget is not None else 570.0
    cap = os.environ.get("GALAH_BENCH_STAGE_CAP")
    if cap:
        budget = min(budget, float(cap))

    from bench import _synth_families
    from galah_tpu.api import generate_galah_clusterer
    from galah_tpu.obs import flow as obs_flow
    from galah_tpu.utils import timing

    # x100 families, NOT the ladder's x4: greedy rounds only engage
    # for preclusters past DENSE_PRECLUSTER_CAP(64) members, so the
    # fused-round comparison needs big preclusters to have rounds to
    # fuse at all (x4 families all take the dense per-precluster path
    # and both sides would measure an empty loop).
    if _left(budget) >= 240:
        n_genomes, n_families = 1000, 10
    else:
        n_genomes, n_families = 200, 2
    paths = _synth_families(n_genomes=n_genomes, genome_len=100_000,
                            n_families=n_families, mut=0.03, seed=11)

    try:
        host_cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        host_cores = os.cpu_count() or 1

    out = {
        "workload": f"{n_genomes} synthetic genomes, {n_families} "
                    "planted families x100, 3% mutation, 100 kbp, "
                    "murmur3 finch+skani, xla sketcher, overlapped, "
                    "rep-rounds=16",
        "n_genomes": n_genomes,
        # On a 1-core host the host and the 'device' share the same
        # core, so host_share measures orchestration fraction, not a
        # transferable wall-clock win — readers must interpret
        # `speedup` and `host_share` against this field.
        "host_cores": host_cores,
        "skipped": [],
    }
    clusterings = {}
    dispatches = {}

    def run_one(mode):
        env_saved = {k: os.environ.get(k)
                     for k in ("GALAH_TPU_MEGAKERNEL", *_PINS)}
        os.environ["GALAH_TPU_MEGAKERNEL"] = \
            "1" if mode == "mega" else "0"
        os.environ.update(_PINS)
        obs_flow.reset()  # per-run flow graph
        try:
            before = timing.GLOBAL.counters()
            t0 = time.perf_counter()
            clusterer = generate_galah_clusterer(list(paths),
                                                 dict(_VALUES))
            clusters = clusterer.cluster()
            dt = time.perf_counter() - t0
            after = timing.GLOBAL.counters()
        finally:
            for k, v in env_saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        clusterings[mode] = clusters
        dispatches[mode] = (after.get("greedy-select-dispatches", 0)
                            - before.get("greedy-select-dispatches", 0))
        out[f"{mode}_genomes_per_sec"] = round(len(paths) / dt, 2)
        out[f"{mode}_seconds"] = round(dt, 3)
        out[f"{mode}_n_clusters"] = len(clusters)
        if mode == "mega":
            out["counters"] = {
                k: after.get(k, 0) - before.get(k, 0)
                for k in _COUNTERS
                if after.get(k, 0) - before.get(k, 0)}
            # the headline gauge: host-vs-device blame over the wall
            fsnap = obs_flow.snapshot()
            if fsnap.get("stages"):
                cp = obs_flow.critical_path(fsnap, dt)
                host = cp.get("host") or {}
                if isinstance(host.get("share"), (int, float)):
                    out["host_share"] = host["share"]
                    out["host_blame_s"] = host.get("blame_s")
                    out["host_share_gate"] = host["share"] < 0.10
                out["bottleneck"] = cp.get("bottleneck")

    # Megakernel first: its compiles are billed to it.
    for mode in ("mega", "off"):
        if _left(budget) < 30:
            out["skipped"].append(mode)
            continue
        try:
            run_one(mode)
        except Exception as e:  # noqa: BLE001 - partial JSON > crash
            out[f"{mode}_error"] = f"{type(e).__name__}: {e}"

    if "mega" in clusterings and "off" in clusterings:
        out["parity"] = clusterings["mega"] == clusterings["off"]
        if out["parity"] and out.get("off_genomes_per_sec"):
            out["speedup"] = round(out["mega_genomes_per_sec"]
                                   / out["off_genomes_per_sec"], 2)
            if host_cores <= 1:
                out["speedup_note"] = (
                    "1-core host: device programs and host "
                    "orchestration share one core, so speedup ~1x is "
                    "the expected ceiling (dispatch_ratio and parity "
                    "are the verdicts here, not the rate)")
        elif not out["parity"]:
            out["speedup"] = 0.0
        if dispatches.get("mega"):
            out["dispatch_ratio"] = round(
                dispatches["off"] / dispatches["mega"], 2)
            out["dispatch_gate"] = out["dispatch_ratio"] >= 4.0

    print("MEGAKERNEL_JSON " + json.dumps(out))


if __name__ == "__main__":
    main()
