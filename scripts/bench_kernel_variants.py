"""Measure the Mosaic pair-stats kernel variants on the live TPU.

Run when the tunnel is healthy; timings force host materialization
(block_until_ready does not block through the tunnel). Decides whether
range_skip should become the default inside tile_stats_pallas.
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def _measure(fn, repeats=3):
    """Warm/compile, then best-of-repeats with a drift check: the
    summed stats must not change between timed calls (forces host
    materialization too — the tunnel's block_until_ready is async)."""
    ref = int(np.asarray(fn()[0]).sum())
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        got = int(np.asarray(fn()[0]).sum())
        best = min(best, time.perf_counter() - t0)
        assert got == ref
    return best


def main():
    import jax
    import jax.numpy as jnp

    from galah_tpu.ops.pairwise import tile_stats
    from galah_tpu.ops.pallas_pairwise import tile_stats_pallas

    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.default_rng(1)
    K = 1000

    def mats(n):
        m = rng.integers(0, 1 << 63, size=(2 * n, K), dtype=np.uint64)
        m.sort(axis=1)
        return jnp.asarray(m[:n]), jnp.asarray(m[n:])

    def run(label, fn, n_pairs):
        # One bad variant (e.g. a worker crash on an oversized XLA
        # gather — seen 2026-07-31 on xla 512x512) must not lose the
        # rest of the capture; later variants fail fast if the client
        # died with it, and the raw log records both.
        try:
            best = _measure(fn)
            print(f"{label}: {best*1e3:.1f} ms = "
                  f"{n_pairs/best:,.0f} pairs/s", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{label}: FAILED {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    # Pairlist kernel first (the sparse production pipeline's exact
    # pass — the most decision-relevant number) vs the vmapped XLA
    # searchsorted on the same gathered pair batch.
    from galah_tpu.ops.pairwise import _pair_stats
    from galah_tpu.ops.pallas_pairlist import pair_stats_pairs_pallas

    m = rng.integers(0, 1 << 63, size=(1024, K), dtype=np.uint64)
    m.sort(axis=1)
    b = 8192
    pa = jnp.asarray(m[rng.integers(0, 1024, size=b)])
    pb = jnp.asarray(m[rng.integers(0, 1024, size=b)])

    @jax.jit
    def xla_pairs(a, bb):
        return jax.vmap(lambda x, y: _pair_stats(x, y, K))(a, bb)

    run(f"pairlist-mosaic B={b}",
        lambda: pair_stats_pairs_pallas(pa, pb, K), b)
    run(f"pairlist-mosaic+skip B={b}",
        lambda: pair_stats_pairs_pallas(pa, pb, K, range_skip=True), b)
    run(f"pairlist-xla B={b}", lambda: xla_pairs(pa, pb), b)

    for n in (128, 512):
        r, c = mats(n)
        run(f"pallas {n}x{n}", lambda: tile_stats_pallas(r, c, K),
            n * n)
        run(f"pallas+skip {n}x{n}",
            lambda: tile_stats_pallas(r, c, K, range_skip=True), n * n)
        if n <= 128:  # xla 512x512 crashed the TPU worker (see above)
            run(f"xla {n}x{n}", lambda: tile_stats(r, c, K, 21), n * n)


if __name__ == "__main__":
    main()
