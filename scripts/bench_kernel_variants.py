"""Measure the Mosaic pair-stats kernel variants on the live TPU.

Run when the tunnel is healthy; timings force host materialization
(block_until_ready does not block through the tunnel). Decides whether
range_skip should become the default inside tile_stats_pallas.
"""

import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    import jax.numpy as jnp

    from galah_tpu.ops.pairwise import tile_stats
    from galah_tpu.ops.pallas_pairwise import tile_stats_pallas

    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.default_rng(1)
    K = 1000

    def mats(n):
        m = rng.integers(0, 1 << 63, size=(2 * n, K), dtype=np.uint64)
        m.sort(axis=1)
        return jnp.asarray(m[:n]), jnp.asarray(m[n:])

    for n in (128, 512):
        r, c = mats(n)
        for label, fn in (
            ("xla", lambda: tile_stats(r, c, K, 21)),
            ("pallas", lambda: tile_stats_pallas(r, c, K)),
            ("pallas+skip",
             lambda: tile_stats_pallas(r, c, K, range_skip=True)),
        ):
            out = fn()
            ref = int(np.asarray(out[0]).sum())  # compile + warm
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                got = int(np.asarray(fn()[0]).sum())
                best = min(best, time.perf_counter() - t0)
            assert got == ref
            print(f"{label} {n}x{n}: {best*1e3:.1f} ms = "
                  f"{n*n/best:,.0f} pairs/s", flush=True)


if __name__ == "__main__":
    main()
