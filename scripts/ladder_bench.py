"""BASELINE.md measurement ladder: end-to-end cluster() wall-clock.

Runs the first rungs of the BASELINE.json config ladder on the current
backend (TPU via the default interpreter; CPU with --cpu) and prints a
markdown table row per rung with stage breakdowns:

  rung 1: the abisko4 fixture set (18 real MAGs, 29 MB) — full two-stage
          pipeline, CheckM quality ordering;
  rung 2: N synthetic genomes with planted family structure
          (default 100; --n to scale), precluster+cluster at 95/90.

Usage: python scripts/ladder_bench.py [--cpu] [--n 100] [--hash tpufast]
"""

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--n", type=int, default=100,
                    help="rung-2 synthetic genome count")
    ap.add_argument("--genome-len", type=int, default=500_000)
    ap.add_argument("--hash", default="murmur3",
                    choices=("murmur3", "tpufast"))
    ap.add_argument("--skip-rung1", action="store_true")
    ap.add_argument("--ani-subsample", type=int, default=1,
                    help="FracMinHash c for the exact-ANI stage")
    ap.add_argument("--rung4", action="store_true",
                    help="also run the quality-ordered rung: synthetic "
                         "CheckM2 report + Parks2020_reduced ranking "
                         "(BASELINE.json rung-4 semantics)")
    ap.add_argument("--repeat-frac", type=float, default=0.0,
                    help="rung 2 becomes the adversarial repeat rung: "
                         "UNRELATED genomes sharing this fraction of "
                         "mobile-element content from one pool "
                         "(bench._synth_repeat_genomes) — the "
                         "collision screen's worst case, for "
                         "wall-clock comparison against the uniform "
                         "rung at equal N*bp")
    ap.add_argument("--mega", action="store_true",
                    help="dense-similarity worst case: ONE planted "
                         "mega-family (every pair >95%% ANI) through "
                         "the DEFAULT skani+skani path — the 'many "
                         "closely related genomes' regime the "
                         "reference advertises "
                         "(reference: README.md:18-26). Replaces "
                         "rung 2; --n sets the family size.")
    args = ap.parse_args()
    if args.mega and args.repeat_frac > 0:
        ap.error("--mega and --repeat-frac are mutually exclusive "
                 "(each replaces rung 2 with a different workload)")

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.setdefault("JAX_ENABLE_X64", "1")
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    from galah_tpu.api import generate_galah_clusterer
    from galah_tpu.utils import timing

    backend = jax.default_backend()
    results = []

    def run(name, paths, values):
        timing.reset()
        t0 = time.perf_counter()
        clusterer = generate_galah_clusterer(paths, values)
        clusters = clusterer.cluster()
        dt = time.perf_counter() - t0
        stages = {name: round(secs, 2)
                  for name, secs, _count in timing.GLOBAL.items()}
        results.append({
            "rung": name, "backend": backend, "n_genomes": len(paths),
            "wall_s": round(dt, 2), "n_clusters": len(clusters),
            "genomes_per_s": round(len(paths) / dt, 3),
            "stages": stages,
            "counters": timing.GLOBAL.counters(),
        })
        print(json.dumps(results[-1]), flush=True)

    base_values = {
        "ani": 95.0, "precluster_ani": 90.0,
        "min_aligned_fraction": 15.0, "fragment_length": 3000,
        "precluster_method": "finch", "cluster_method": "skani",
        "threads": 4, "hash_algorithm": args.hash,
        "ani_subsample": args.ani_subsample,
    }

    if not args.skip_rung1:
        DATA = "/root/reference/tests/data/abisko4"
        paths = sorted(glob.glob(f"{DATA}/*.fna"))
        values = dict(base_values)
        values["checkm_tab_table"] = f"{DATA}/abisko4.csv"
        values["quality_formula"] = "Parks2020_reduced"
        run("rung1-abisko18", paths, values)

    # rung 2: synthetic planted families
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    import importlib

    bench = importlib.import_module("bench")
    if args.mega:
        # All N genomes are ~2%-mutated copies of ONE base, so every
        # pair sits near 96% ANI and NOTHING screens out: the collision
        # screen's mega-run dedup, the single giant precluster's
        # transform_ids, and the greedy phase on one huge candidate
        # list are all on the hot path. Default config (skani+skani).
        paths = bench._synth_families(
            n_genomes=args.n, genome_len=args.genome_len,
            n_families=1, mut=0.02, seed=11)
        values = dict(base_values)
        values["precluster_method"] = "skani"
        values["cluster_method"] = "skani"
        run(f"rung-mega-{args.n}", paths, values)
    elif args.repeat_frac > 0:
        paths = bench._synth_repeat_genomes(
            n_genomes=args.n, genome_len=args.genome_len,
            repeat_frac=args.repeat_frac, seed=23)
        run(f"rung-repeat{args.repeat_frac:g}-{args.n}", paths,
            dict(base_values))
    else:
        n_fam = max(args.n // 4, 1)
        paths = bench._synth_families(
            n_genomes=args.n, genome_len=args.genome_len,
            n_families=n_fam, mut=0.03, seed=11)
        run(f"rung2-synthetic-{args.n}", paths, dict(base_values))

    if args.rung4:
        # rung 4 semantics: quality-ordered greedy rep selection from a
        # CheckM2-style quality report (BASELINE.json rung 4 uses 10k
        # MAGs + CheckM2; this synthesizes the same pipeline shape at
        # --n genomes so the quality path is measured, not just the
        # distance path).
        import numpy as np

        rng = np.random.default_rng(13)
        qpath = os.path.join(os.path.dirname(paths[0]),
                             "quality_report.tsv")
        with open(qpath, "w") as fh:
            fh.write("Name\tCompleteness\tContamination\n")
            for p in paths:
                stem = os.path.splitext(os.path.basename(p))[0]
                comp = rng.uniform(60.0, 100.0)
                cont = rng.uniform(0.0, 8.0)
                fh.write(f"{stem}\t{comp:.2f}\t{cont:.2f}\n")
        values = dict(base_values)
        values["checkm2_quality_report"] = qpath
        values["quality_formula"] = "Parks2020_reduced"
        values["min_completeness"] = 50.0
        values["max_contamination"] = 10.0
        run(f"rung4-quality-{args.n}", paths, values)

    print("\n| rung | backend | N | wall (s) | genomes/s | clusters |")
    print("|---|---|---|---|---|---|")
    for r in results:
        print(f"| {r['rung']} | {r['backend']} | {r['n_genomes']} | "
              f"{r['wall_s']} | {r['genomes_per_s']} | "
              f"{r['n_clusters']} |")


if __name__ == "__main__":
    main()
