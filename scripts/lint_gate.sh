#!/bin/bash
# Pre-commit lint gate: lint only the files git considers changed
# (staged, unstaged, untracked). Checkers still load the whole tree so
# cross-module rules (lock order, flag registry) stay sound — only the
# REPORTING is scoped, and the slow shapes family is skipped unless
# kernel/op code changed. Exit 1 iff a changed file carries an
# unsuppressed WARNING-or-worse finding.
#
# Install as a git hook:   ln -s ../../scripts/lint_gate.sh .git/hooks/pre-commit
# Run by hand:             scripts/lint_gate.sh [--json] [extra lint args]
set -u
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
exec python -m galah_tpu.analysis --changed-only "$@"
