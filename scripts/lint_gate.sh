#!/bin/bash
# Pre-commit lint gate: lint only the files git considers changed
# (staged, unstaged, untracked). Checkers still load the whole tree so
# cross-module rules (lock order, flag registry, the GL11xx effect
# auditors) stay sound — only the REPORTING is scoped, and the slow
# shapes family is skipped unless kernel/op code changed (or an IR
# cache is configured, which makes the warm shapes verdict cheap).
# Exit 1 iff a changed file carries an unsuppressed WARNING-or-worse
# finding.
#
# Install as a git hook:   ln -s ../../scripts/lint_gate.sh .git/hooks/pre-commit
# Run by hand:             scripts/lint_gate.sh [--json] [extra lint args]
#
# --ir-cache-dir DIR: content-hash cache for the per-file GalahIR
# entries and the GL5xx shapes verdict (env twin: GALAH_TPU_IR_CACHE).
# A warm cache cuts the full-lint wall by the whole jax-tracing cost.
#
# --self-check [DIR]: cold-vs-warm cache audit. Runs the FULL lint
# twice against a fresh cache directory (cold populates, warm must
# hit) and fails unless warm wall <= 60% of cold — the acceptance
# bound the IR cache exists to meet. DIR defaults to a temp dir.
#
# --san: instead of the static lint, run the bounded GalahSan smoke —
# the sanitizer reproducer suite plus the obs tests (the most
# lock-heavy tier-1 subset) under GALAH_SAN=1. Exit 1 on any
# violation; the gate test in tests/test_sanitizer.py enforces zero.
set -u
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
if [ "${1:-}" = "--san" ]; then
    shift
    export GALAH_SAN=1
    exec python -m pytest tests/test_sanitizer.py tests/test_obs.py \
        -q -m 'not slow' -p no:cacheprovider "$@"
fi
if [ "${1:-}" = "--self-check" ]; then
    shift
    CACHE_DIR="${1:-$(mktemp -d)}"
    [ $# -gt 0 ] && shift
    rm -rf "$CACHE_DIR" && mkdir -p "$CACHE_DIR"
    now_ms() { python -c 'import time; print(int(time.monotonic()*1000))'; }
    echo "lint self-check: cold run (populating $CACHE_DIR)"
    T0=$(now_ms)
    python -m galah_tpu.analysis --ir-cache-dir "$CACHE_DIR" "$@" \
        || exit 1
    T1=$(now_ms)
    echo "lint self-check: warm run"
    python -m galah_tpu.analysis --ir-cache-dir "$CACHE_DIR" "$@" \
        || exit 1
    T2=$(now_ms)
    COLD=$((T1 - T0)); WARM=$((T2 - T1))
    echo "lint self-check: cold ${COLD}ms, warm ${WARM}ms"
    # acceptance bound: warm (IR-cached) wall <= 60% of cold
    if [ $((WARM * 100)) -gt $((COLD * 60)) ]; then
        echo "lint self-check: FAIL - warm run is >60% of cold" \
             "(cache not effective)" >&2
        exit 1
    fi
    echo "lint self-check: OK (warm is $((WARM * 100 / COLD))% of cold)"
    exit 0
fi
exec python -m galah_tpu.analysis --changed-only "$@"
