#!/bin/bash
# Pre-commit lint gate: lint only the files git considers changed
# (staged, unstaged, untracked). Checkers still load the whole tree so
# cross-module rules (lock order, flag registry) stay sound — only the
# REPORTING is scoped, and the slow shapes family is skipped unless
# kernel/op code changed. Exit 1 iff a changed file carries an
# unsuppressed WARNING-or-worse finding.
#
# Install as a git hook:   ln -s ../../scripts/lint_gate.sh .git/hooks/pre-commit
# Run by hand:             scripts/lint_gate.sh [--json] [extra lint args]
#
# --san: instead of the static lint, run the bounded GalahSan smoke —
# the sanitizer reproducer suite plus the obs tests (the most
# lock-heavy tier-1 subset) under GALAH_SAN=1. Exit 1 on any
# violation; the gate test in tests/test_sanitizer.py enforces zero.
set -u
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
if [ "${1:-}" = "--san" ]; then
    shift
    export GALAH_SAN=1
    exec python -m pytest tests/test_sanitizer.py tests/test_obs.py \
        -q -m 'not slow' -p no:cacheprovider "$@"
fi
exec python -m galah_tpu.analysis --changed-only "$@"
