"""Host FASTA ingestion at multi-Gbp: measure the named north-star risk.

BASELINE.md's 50k-genome extrapolation names host-side FASTA ingestion
(~175 Gbp) as "the open risk" on an assumed ~100 MB/s/core. This bench
replaces the assumption with measurements at real scale:

  1. single-thread C-parser throughput (csrc/ingest.c via
     io/fasta.read_genome) over a generated multi-Gbp corpus;
  2. thread-pool ingestion (the ctypes call releases the GIL, so a
     multicore host parses that many files concurrently — measured
     with the machine's actual core count, recorded in the output);
  3. gzipped-input throughput (the reference ingests .gz via
     needletail the same way, reference: src/genome_stats.rs:1-51);
  4. the REAL per-host ingestion split (parallel/distributed.host_shard)
     driven by two actual jax.distributed processes, each ingesting
     >= 1 Gbp of its own file slice.

Usage: python scripts/bench_ingest.py [--gbp 10] [--files 24]
       [--keep] [--skip-dist]
Prints one JSON line per measurement and INGEST_JSON with the summary.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_DIST_WORKER = r"""
import os, sys, time
coord, n_proc, pid, listfile = sys.argv[1:5]
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=coord,
                           num_processes=int(n_proc),
                           process_id=int(pid))
from galah_tpu.io.fasta import read_genome
from galah_tpu.parallel import distributed

paths = [line.strip() for line in open(listfile) if line.strip()]
mine = distributed.host_shard(paths)
t0 = time.perf_counter()
total_bp = 0
for p in mine:
    total_bp += read_genome(p).codes.shape[0]
dt = time.perf_counter() - t0
print(f"RESULT pid={pid} files={len(mine)} bp={total_bp} "
      f"wall={dt:.2f}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def make_corpus(outdir: str, gbp: float, n_files: int) -> list:
    """Write n_files FASTA files totaling ~gbp Gbp.

    One 64 Mbp random block is generated once and written at rotating
    offsets (content repetition is irrelevant to parser throughput;
    generation at numpy speed would otherwise dominate the setup).
    Contigs are 4 Mbp with 80-col-free long lines plus a sprinkling of
    N's so the ambiguity counter is exercised."""
    os.makedirs(outdir, exist_ok=True)
    rng = np.random.default_rng(0)
    block_bp = 64 << 20
    lut = np.frombuffer(b"ACGT", dtype=np.uint8)
    block = lut[rng.integers(0, 4, size=block_bp)]
    block[rng.integers(0, block_bp, size=1000)] = ord("N")
    blk = block.tobytes()

    per_file = int(gbp * 1e9 / n_files)
    contig = 4 << 20
    paths = []
    for f in range(n_files):
        p = os.path.join(outdir, f"g{f:03d}.fna")
        paths.append(p)
        if os.path.exists(p) and os.path.getsize(p) > per_file:
            continue  # --keep rerun
        with open(p, "wb") as fh:
            written = 0
            c = 0
            while written < per_file:
                n = min(contig, per_file - written)
                off = (f * 7919 + c * 104729) % (block_bp - n) \
                    if block_bp > n else 0
                fh.write(b">contig%d\n" % c)
                fh.write(blk[off:off + n])
                fh.write(b"\n")
                written += n
                c += 1
    return paths


def _gzip_subset(paths: list, n: int) -> list:
    """Gzip the first n corpus files (idempotent), return the .gz paths."""
    out = []
    for p in paths[:n]:
        gz = p + ".gz"
        if not os.path.exists(gz):
            subprocess.run(["gzip", "-1", "-k", "-f", p], check=True)
        out.append(gz)
    return out


def run_ingest_variants(args) -> dict:
    """The ingest_variants bench stage: end-to-end ingest+sketch Mbp/s
    by strategy x workers x gzip, against the serial-prologue baseline
    (read everything, then sketch everything — the pipeline shape
    before the streaming subsystem), with the host/device cost split.

    The full >= --sketch-gbp corpus runs through the streamed AUTO
    pipeline (the headline + speedup_vs_serial number); the variant
    matrix and the baselines run on a subset so the stage fits its
    budget. Self-budgeting: once --budget seconds elapse, remaining
    variants are skipped (recorded in "skipped")."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from galah_tpu.backends.minhash_backend import SketchStore
    from galah_tpu.io.diskcache import CacheDir
    from galah_tpu.io.fasta import read_genome
    from galah_tpu.ops import sketch_stream

    t_start = time.perf_counter()

    def remaining() -> float:
        if not args.budget:
            return float("inf")
        return args.budget - (time.perf_counter() - t_start)

    # ~4.3 Mbp per file (a realistic microbial assembly, and an
    # awkward size for pow2 chunk padding) -> multi-file corpus
    per_file_bp = 4_300_000
    n_files = max(8, int(args.sketch_gbp * 1e9 / per_file_bp))
    paths = make_corpus(args.dir, args.sketch_gbp, n_files)
    total_bp_est = int(args.sketch_gbp * 1e9)
    subset = paths[:max(4, int(args.variants_mbp * 1e6
                               // per_file_bp))]
    out = {
        "sketch_gbp": args.sketch_gbp,
        "n_files": len(paths),
        "per_file_mbp": round(per_file_bp / 1e6, 1),
        "subset_files": len(subset),
        "n_cores": os.cpu_count() or 1,
        "variants": {},
        "skipped": [],
    }

    def fresh_store() -> SketchStore:
        import tempfile

        return SketchStore(1000, 21,
                           cache=CacheDir(tempfile.mkdtemp()))

    def streamed(ps, workers, strategy=None):
        store = fresh_store()
        t0 = time.perf_counter()
        bp = 0
        for _p, _s in sketch_stream.iter_path_sketches(
                ps, store, threads=workers, strategy=strategy):
            pass
        bp = sum(read_bp.get(p, 0) for p in ps) or None
        dt = time.perf_counter() - t0
        return dt, bp

    read_bp: dict = {}

    # 1. serial-prologue baseline (subset): read ALL files, then one
    # batched device sketch pass — the historical device-pipeline
    # shape this PR replaces. Host/device split = read wall vs rest.
    label = "serial_prologue_xla"
    if remaining() > 0:
        store = fresh_store()
        t0 = time.perf_counter()
        gs = [(p, read_genome(p)) for p in subset]
        t_read = time.perf_counter() - t0
        for p, g in gs:
            read_bp[p] = int(g.codes.shape[0])
        store.sketch_batch_only(gs)
        dt = time.perf_counter() - t0
        bp = sum(read_bp[p] for p in subset)
        out["variants"][label] = {
            "mbp_s": round(bp / 1e6 / dt, 2),
            "host_read_s": round(t_read, 2),
            "device_sketch_s": round(dt - t_read, 2),
            "wall_s": round(dt, 2), "workers": 1}
        del gs
    else:
        out["skipped"].append(label)

    # 2. serial-prologue C baseline (subset): the historical
    # single-device-CPU shape (per-genome C bottom-k after the read).
    label = "serial_prologue_c"
    if remaining() > 0 and sketch_stream._c_sketcher_available():
        store = fresh_store()
        t0 = time.perf_counter()
        gs = [(p, read_genome(p)) for p in subset]
        t_read = time.perf_counter() - t0
        for _p, g in gs:
            store.sketch_only(g)
        dt = time.perf_counter() - t0
        bp = sum(read_bp[p] for p in subset)
        out["variants"][label] = {
            "mbp_s": round(bp / 1e6 / dt, 2),
            "host_read_s": round(t_read, 2),
            "host_sketch_s": round(dt - t_read, 2),
            "wall_s": round(dt, 2), "workers": 1}
        del gs
    else:
        out["skipped"].append(label)

    # 3. streamed variant matrix (subset): strategy x workers. AUTO
    # resolves per backend (the C bottom-k on this single-device CPU
    # box); the xla pin records the chunked device path for the
    # speedup denominator's sanity.
    matrix = [("auto", None, 1), ("auto", None, 2),
              ("xla", "xla", 2)]
    for name, strat, workers in matrix:
        label = f"streamed_{name}_w{workers}"
        if remaining() <= 0:
            out["skipped"].append(label)
            continue
        dt, _ = streamed(subset, workers, strat)
        bp = sum(read_bp.get(p, 0) for p in subset)
        out["variants"][label] = {
            "mbp_s": round(bp / 1e6 / dt, 2) if bp else None,
            "wall_s": round(dt, 2), "workers": workers,
            "strategy": name}
        print(json.dumps({label: out["variants"][label]}), flush=True)

    # 4. gzip subset through the streamed AUTO pipeline: byte-identical
    # sketches at whatever the decompressor adds to the host cost.
    label = "streamed_auto_gzip"
    if remaining() > 0:
        gz = _gzip_subset(subset, min(8, len(subset)))
        plain_bp = sum(read_bp.get(p, 0)
                       for p in subset[:len(gz)])
        dt, _ = streamed(gz, 2, None)
        out["variants"][label] = {
            "mbp_s": round(plain_bp / 1e6 / dt, 2) if plain_bp else None,
            "wall_s": round(dt, 2), "workers": 2, "files": len(gz)}
    else:
        out["skipped"].append(label)

    # 5. the >= 1 Gbp headline: the whole corpus through the streamed
    # AUTO pipeline, overlapped ingest + sketch.
    label = "overlapped_full_corpus"
    if remaining() > 0:
        dt, _ = streamed(paths, 2, None)
        out["variants"][label] = {
            "mbp_s": round(total_bp_est / 1e6 / dt, 2),
            "wall_s": round(dt, 2), "workers": 2,
            "gbp": args.sketch_gbp}
        base = out["variants"].get("serial_prologue_xla")
        if base and base["mbp_s"]:
            out["speedup_vs_serial"] = round(
                out["variants"][label]["mbp_s"] / base["mbp_s"], 2)
        out["overlapped_mbp_s"] = out["variants"][label]["mbp_s"]
    else:
        out["skipped"].append(label)
    if "serial_prologue_xla" in out["variants"]:
        out["serial_prologue_mbp_s"] = \
            out["variants"]["serial_prologue_xla"]["mbp_s"]
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gbp", type=float, default=10.0)
    ap.add_argument("--files", type=int, default=24)
    ap.add_argument("--dir", default="/tmp/galah_ingest_bench")
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("--skip-dist", action="store_true")
    ap.add_argument("--variants", action="store_true",
                    help="run the ingest_variants sketch matrix "
                         "instead of the raw-parser measurements")
    ap.add_argument("--budget", type=float, default=0.0,
                    help="self-budget in seconds for --variants")
    ap.add_argument("--sketch-gbp", type=float, default=1.1,
                    help="--variants corpus size (>= 1 Gbp for the "
                         "acceptance headline)")
    ap.add_argument("--variants-mbp", type=float, default=90.0,
                    help="--variants subset size for the matrix and "
                         "baselines")
    args = ap.parse_args()

    if args.variants:
        out = run_ingest_variants(args)
        print("INGEST_JSON " + json.dumps(out), flush=True)
        if not args.keep:
            import shutil

            shutil.rmtree(args.dir, ignore_errors=True)
        return

    import jax

    jax.config.update("jax_platforms", "cpu")
    from galah_tpu.io.fasta import read_genome

    ncores = os.cpu_count() or 1
    out = {"gbp": args.gbp, "n_files": args.files, "n_cores": ncores}

    t0 = time.perf_counter()
    paths = make_corpus(args.dir, args.gbp, args.files)
    total_bytes = sum(os.path.getsize(p) for p in paths)
    print(json.dumps({"setup_s": round(time.perf_counter() - t0, 1),
                      "corpus_gb": round(total_bytes / 1e9, 2)}),
          flush=True)

    # 1. single-thread sequential ingest
    t0 = time.perf_counter()
    total_bp = 0
    for p in paths:
        total_bp += read_genome(p).codes.shape[0]
    dt = time.perf_counter() - t0
    out["single_thread_mb_per_s"] = round(total_bytes / dt / 1e6, 1)
    out["single_thread_bp_per_s"] = round(total_bp / dt, 0)
    out["single_thread_wall_s"] = round(dt, 2)
    print(json.dumps({"single_thread": out["single_thread_mb_per_s"],
                      "unit": "MB/s"}), flush=True)

    # 2. thread-pool ingest (ctypes releases the GIL during the C call)
    from concurrent.futures import ThreadPoolExecutor

    workers = max(2, ncores)
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=workers) as pool:
        bps = list(pool.map(
            lambda p: read_genome(p).codes.shape[0], paths))
    dt = time.perf_counter() - t0
    assert sum(bps) == total_bp
    out["threaded_workers"] = workers
    out["threaded_mb_per_s"] = round(total_bytes / dt / 1e6, 1)
    out["threaded_wall_s"] = round(dt, 2)
    print(json.dumps({"threaded": out["threaded_mb_per_s"],
                      "workers": workers, "unit": "MB/s"}), flush=True)

    # 3. gzip ingest on the first file
    gz = paths[0] + ".gz"
    if not os.path.exists(gz):
        subprocess.run(["gzip", "-1", "-k", "-f", paths[0]], check=True)
    gz_bytes = os.path.getsize(gz)
    t0 = time.perf_counter()
    bp = read_genome(gz).codes.shape[0]
    dt = time.perf_counter() - t0
    out["gzip_mb_per_s_compressed"] = round(gz_bytes / dt / 1e6, 1)
    out["gzip_bp_per_s"] = round(bp / dt, 0)
    print(json.dumps({"gzip_bp_per_s": out["gzip_bp_per_s"]}),
          flush=True)

    # 4. the real per-host split: 2 jax.distributed processes
    if not args.skip_dist:
        listfile = os.path.join(args.dir, "files.txt")
        with open(listfile, "w") as fh:
            fh.write("\n".join(paths))
        coord = f"127.0.0.1:{_free_port()}"
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _DIST_WORKER, coord, "2",
                 str(pid), listfile],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env, cwd=REPO)
            for pid in range(2)
        ]
        lines = []
        ok = True
        for p in procs:
            so, se = p.communicate(timeout=3600)
            ok &= p.returncode == 0
            lines += [ln for ln in so.splitlines()
                      if ln.startswith("RESULT")]
            if p.returncode != 0:
                print(se[-500:], file=sys.stderr)
        dt = time.perf_counter() - t0
        out["dist_2proc_ok"] = ok
        out["dist_2proc_wall_s"] = round(dt, 2)
        out["dist_2proc_mb_per_s"] = round(total_bytes / dt / 1e6, 1)
        for ln in lines:
            print(ln, flush=True)

    print("INGEST_JSON " + json.dumps(out), flush=True)
    if not args.keep:
        import shutil

        shutil.rmtree(args.dir, ignore_errors=True)


if __name__ == "__main__":
    main()
