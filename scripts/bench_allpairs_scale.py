"""All-pairs scaling: 1-D vs 2D tiled mesh, with the HLL
cardinality-band prefilter's pruning fraction.

The 2D tiled mesh (GALAH_TPU_MESH_SHAPE, parallel/mesh.py) replicates
each sketch row only along its mesh row and column — (r-1)+(c-1)
interconnect crossings instead of the 1-D mesh's n_dev-1 — so the
per-row DCN bytes drop by ~2*sqrt(D)/D while the pair set stays
bit-identical. This stage prices exactly that on synthetic sorted
uint64 sketch matrices at N in {1k, 5k, 20k}:

  * candidate pairs/s for the 1-D and the 2D (squarest) mesh through
    ``sharded_threshold_pairs`` (XLA tiles — the CPU-sim twin of the
    production pass), 2D run FIRST so its compiles land inside its
    own timing;
  * the modeled ``mesh.dcn_bytes_per_row`` gauge for both meshes and
    their ratio (the communication-avoiding claim, acceptance bound
    2*sqrt(D)/D);
  * a pair-set parity bit per rung — a 2D mesh that returns a
    different pair set zeroes the speedup field;
  * the ``precluster.bucket_pruned_fraction`` of the cardinality-band
    prefilter (ops/bucketing.py) on a log-uniform skewed-cardinality
    corpus at the same N.

Self-budgeting like the variant matrices: rungs are priced largest-
last and skipped (recorded in `skipped`) when the remaining budget
cannot cover them; a partial run still prints ALLPAIRS_JSON with what
it measured.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_T0 = time.monotonic()

_K = 512          # sketch width: smallest with a finite band width at
                  # min_ani=0.95 (K=128's 6-sigma MinHash margin
                  # swallows the threshold -> zero pruning), still
                  # tractable at the 20k rung on CPU sim
_MIN_ANI = 0.95
_KMER = 21

# (n, rough CPU-sim cost in seconds for both mesh passes + bucketing;
# ~18k candidate pairs/s at K=512 on the 8-device CPU sim, so the 5k
# and 20k rungs only run under a widened budget — TPU hardware runs
# them orders of magnitude faster)
_RUNGS = ((1_000, 120), (5_000, 2_000), (20_000, 24_000))


def _left(budget):
    return budget - (time.monotonic() - _T0)


def _corpus(n, rng):
    import numpy as np

    mat = np.sort(rng.integers(0, 1 << 62, size=(n, _K),
                               dtype=np.uint64), axis=1)
    # planted near-duplicates so the pair set is non-empty at any N
    for i in range(8):
        a, b = i, n - 1 - i
        mat[b] = mat[a].copy()
        mat[b, :8] = rng.integers(0, 1 << 62, size=8, dtype=np.uint64)
        mat[b] = np.sort(mat[b])
    cards = np.exp(rng.uniform(np.log(1e3), np.log(1e8), size=n))
    for i in range(8):
        cards[n - 1 - i] = cards[i] * 1.1
    return mat, cards


def _run_rung(n, out):
    import numpy as np

    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.ops.bucketing import bucketed_threshold_pairs
    from galah_tpu.parallel.mesh import (_squarest_factorization,
                                         make_mesh, make_mesh_2d,
                                         sharded_threshold_pairs)

    rng = np.random.default_rng(17)
    mat, cards = _corpus(n, rng)
    n_dev = len(__import__("jax").devices())
    shape = _squarest_factorization(n_dev)
    rung = {"n": n, "n_devices": n_dev,
            "mesh_shape": f"{shape[0]}x{shape[1]}"}
    candidates = n * (n - 1) / 2.0
    pair_sets = {}

    # 2D first: its compiles are billed to it (conservative speedup).
    for label, mesh in (("2d", make_mesh_2d(shape)),
                        ("1d", make_mesh(n_dev))):
        t0 = time.perf_counter()
        pairs = sharded_threshold_pairs(mat, _KMER, _MIN_ANI, mesh,
                                        use_pallas=False)
        dt = time.perf_counter() - t0
        pair_sets[label] = pairs
        rung[f"{label}_pairs_per_sec"] = round(candidates / dt, 1)
        rung[f"{label}_seconds"] = round(dt, 3)
        rung[f"{label}_dcn_bytes_per_row"] = obs_metrics.snapshot()[
            "mesh.dcn_bytes_per_row"]["value"]

    rung["n_pairs"] = len(pair_sets["1d"])
    rung["parity"] = pair_sets["2d"] == pair_sets["1d"]
    rung["dcn_ratio"] = round(rung["2d_dcn_bytes_per_row"]
                              / rung["1d_dcn_bytes_per_row"], 4)
    if rung["parity"]:
        rung["speedup_2d"] = round(rung["2d_pairs_per_sec"]
                                   / rung["1d_pairs_per_sec"], 2)
    else:
        rung["speedup_2d"] = 0.0

    bucketed = bucketed_threshold_pairs(mat, cards, k=_KMER,
                                        min_ani=_MIN_ANI,
                                        sketch_size=_K)
    snap = obs_metrics.snapshot()
    rung["bucket_pruned_fraction"] = round(
        snap["precluster.bucket_pruned_fraction"]["value"], 4)
    rung["bucket_count"] = snap["precluster.bucket_count"]["value"]
    rung["bucket_parity"] = bucketed == pair_sets["1d"]
    out["rungs"].append(rung)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=None,
                    help="seconds for the whole stage (default 570, "
                         "capped by GALAH_BENCH_STAGE_CAP)")
    args = ap.parse_args()

    budget = args.budget if args.budget is not None else 570.0
    cap = os.environ.get("GALAH_BENCH_STAGE_CAP")
    if cap:
        budget = min(budget, float(cap))

    out = {
        "workload": f"synthetic sorted uint64 sketches, K={_K}, "
                    f"k={_KMER}, min_ani={_MIN_ANI}, 8 planted "
                    "near-duplicate pairs, log-uniform 1e3..1e8 "
                    "cardinalities",
        "rungs": [],
        "skipped": [],
    }
    for n, cost in _RUNGS:
        if _left(budget) < cost:
            out["skipped"].append(n)
            continue
        try:
            _run_rung(n, out)
        except Exception as e:  # noqa: BLE001 - partial JSON > crash
            out[f"n{n}_error"] = f"{type(e).__name__}: {e}"

    print("ALLPAIRS_JSON " + json.dumps(out))


if __name__ == "__main__":
    main()
