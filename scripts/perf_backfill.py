"""Seed the perf ledger from the historical BENCH_r*/MULTICHIP_r* rounds.

The ledger (galah_tpu/obs/ledger.py) starts empty; `galah-tpu perf
check` refuses a verdict below MIN_HISTORY entries per key. The repo
already carries five rounds of bench and multichip captures as loose
JSON (BENCH_r01-r05.json, MULTICHIP_r01-r05.json) — this script
converts them into ledger entries once, so the first gated run has
real history instead of an insufficient-history pass-through.

Legacy-error sanitation: rounds 2-5 recorded the probe failure as the
verbatim ``TimeoutExpired`` message, which embeds the full subprocess
command repr. bench.py now records the one-line token
(``backend=cpu-fallback reason=probe-timeout``); the backfill maps the
legacy text to the same token so the seeded history and the live
format agree (the error COUNT is what becomes the `bench.errors`
metric either way).

Idempotent: entries carry a ``src_file`` field and a file already
present in the ledger is skipped, so re-running the script never
duplicates history. Timestamps come from file mtime (the rounds
predate the ledger; no recorded wall clock exists) and ``sha`` is None
— the legacy artifacts do not say which commit produced them.

Usage::

    python scripts/perf_backfill.py [--ledger PATH] [--root DIR]

``--ledger`` defaults to $GALAH_OBS_LEDGER or perf_ledger.jsonl in the
repo root. No jax import — runs on any host.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from galah_tpu.obs import ledger  # noqa: E402

#: bench.py workload constants at the time the rounds were captured
#: (bench.py PRODUCTION_N / SKETCH_SIZE) — the legacy JSON predates the
#: workload gauges, so the fingerprint is pinned here.
LEGACY_N = 4096
LEGACY_K = 1000

LEGACY_PROBE_TOKEN = "backend=cpu-fallback reason=probe-timeout"


def sanitize_error(err: str) -> str:
    """Map a legacy verbatim probe error to the one-line token format.

    Anything that is already one `key=value`-style line passes
    through; the TimeoutExpired command-repr lines collapse to the
    probe-timeout token, other probe failures to their type name."""
    if "\n" not in err and " " not in err:
        return err
    if "probe failed" in err or "backend probe" in err:
        if "TimeoutExpired" in err or "timed out" in err:
            return LEGACY_PROBE_TOKEN
        exc = err.split("probe failed:", 1)[-1].strip()
        exc_type = exc.split(":", 1)[0].strip() or "ProbeError"
        return f"backend=cpu-fallback reason={exc_type}"
    # Non-probe stage errors keep their stage prefix but lose command
    # reprs / newlines: first line, whitespace-normalized.
    return " ".join(err.splitlines()[0].split())[:200]


def bench_entry(path: str) -> "dict | None":
    with open(path) as fh:
        doc = json.load(fh)
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict):
        return None  # round never produced a bench line (e.g. r01)
    metrics = {}
    metric_name = parsed.get("metric")
    value = parsed.get("value")
    if metric_name and isinstance(value, (int, float)):
        metrics[f"bench.{metric_name}"] = float(value)
    vs = parsed.get("vs_baseline")
    if isinstance(vs, (int, float)):
        metrics["bench.vs_baseline"] = float(vs)
    for name, v in (parsed.get("stages") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            metrics[f"bench.{name}"] = float(v)
    errors = [sanitize_error(e) for e in parsed.get("errors") or []]
    metrics["bench.errors"] = float(len(errors))
    if not metrics:
        return None
    return {
        "v": ledger.LEDGER_VERSION,
        "ts": os.path.getmtime(path),
        "sha": None,
        "src_file": os.path.basename(path),
        "errors": errors,
        "key": {
            "backend": parsed.get("backend"),
            "device_kind": None,
            "n_devices": parsed.get("n_devices"),
            "workload": {"n": parsed.get("n_genomes", LEGACY_N),
                         "k": LEGACY_K, "p": None},
            "strategy": "auto/auto/auto",
            "source": "bench-backfill",
        },
        "metrics": metrics,
    }


def multichip_entry(path: str) -> "dict | None":
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("skipped"):
        return None
    metrics = {
        "multichip.ok": 1.0 if doc.get("ok") else 0.0,
        "multichip.rc": float(doc.get("rc", -1)),
    }
    return {
        "v": ledger.LEDGER_VERSION,
        "ts": os.path.getmtime(path),
        "sha": None,
        "src_file": os.path.basename(path),
        "key": {
            "backend": "multichip-dryrun",
            "device_kind": None,
            "n_devices": doc.get("n_devices"),
            "workload": {"n": None, "k": None, "p": None},
            "strategy": "auto/auto/auto",
            "source": "multichip-backfill",
        },
        "metrics": metrics,
    }


def main(argv=None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ledger",
                    default=os.environ.get("GALAH_OBS_LEDGER")
                    or os.path.join(repo_root, "perf_ledger.jsonl"))
    ap.add_argument("--root", default=repo_root,
                    help="directory holding the BENCH_r*/MULTICHIP_r* "
                         "JSON rounds")
    args = ap.parse_args(argv)

    existing, skipped_lines = ledger.read(args.ledger)
    seen = {e.get("src_file") for e in existing if e.get("src_file")}
    if skipped_lines:
        print(f"note: {skipped_lines} torn/corrupt ledger line(s) "
              "ignored", file=sys.stderr)

    added = 0
    rounds = (sorted(glob.glob(os.path.join(args.root, "BENCH_r*.json")))
              + sorted(glob.glob(os.path.join(args.root,
                                              "MULTICHIP_r*.json"))))
    for path in rounds:
        name = os.path.basename(path)
        if name in seen:
            print(f"skip {name}: already in ledger")
            continue
        entry = (bench_entry(path) if name.startswith("BENCH")
                 else multichip_entry(path))
        if entry is None:
            print(f"skip {name}: no usable payload")
            continue
        ledger.append(args.ledger, entry)
        added += 1
        print(f"seeded {name} -> {args.ledger} "
              f"({len(entry['metrics'])} metrics)")
    print(f"done: {added} entries added, ledger now has "
          f"{len(existing) + added} entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
