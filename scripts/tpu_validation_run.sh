#!/bin/bash
# Opportunistic TPU validation: wait for a responsive tunnel, then run
# the hardware kernel validation, the benchmark, and the TPU ladder in
# sequence. Everything logs to scripts/tpu_validation.log (gitignored,
# live) AND to a dated capture dir under docs/artifacts/ (tracked) so
# a successful session is committable as-is.
set -u
LOG=/root/repo/scripts/tpu_validation.log

# SINGLE-CLIENT TUNNEL LOCK: the round-5 08:39 capture died when two
# clients shared one chip (a manual run raced the watcher's). Every
# invocation path re-execs itself under an exclusive flock on the
# shared lock file, held for the whole session, so every tunnel-using
# child (probe, pytest, bench, ladder) runs single-client by
# construction. GALAH_TUNNEL_LOCKED short-circuits the re-exec when a
# caller (the watcher) already wrapped us in the same lock.
LOCKFILE=${GALAH_TPU_TUNNEL_LOCK:-/tmp/galah_tpu_tunnel.lock}
if [ "${GALAH_TUNNEL_LOCKED:-}" != 1 ]; then
  echo "=== acquiring tunnel lock $LOCKFILE $(date -u) ===" >> "$LOG"
  # flock exits 1 if the wait expires (another client held the chip
  # past 300 s) and that becomes this script's exit status.
  exec env GALAH_TUNNEL_LOCKED=1 flock -w 300 "$LOCKFILE" bash "$0" "$@"
fi

ART=/root/repo/docs/artifacts/tpu_watch_$(date -u +%Y%m%d_%H%M)
cd /root/repo
echo "=== tpu_validation_run (tunnel lock held) $(date -u) ===" >> "$LOG"

# Cross-run perf ledger: every stage's finalized run report appends
# one entry (galah_tpu/obs/ledger.py), so hardware sessions build the
# history `galah-tpu perf check` gates on. The ledger lives outside
# the capture dir — it spans sessions by design.
export GALAH_OBS_LEDGER=${GALAH_OBS_LEDGER:-/root/repo/perf_ledger.jsonl}

for attempt in $(seq 1 60); do
  t0=$(date +%s)
  # 240 s: a slow-but-alive tunnel can take minutes to attach after an
  # outage (the round-3 hardware gate passed at 143 s of runtime) — the
  # watcher must not fail a probe the test gate would have survived.
  if timeout -k 5 240 python -c "import jax; jax.devices()" 2>/dev/null; then
    dt=$(( $(date +%s) - t0 ))
    echo "probe ok in ${dt}s (attempt $attempt) $(date -u)" >> "$LOG"
    break
  fi
  echo "probe failed (attempt $attempt) $(date -u)" >> "$LOG"
  sleep 120
  if [ "$attempt" = 60 ]; then echo "giving up" >> "$LOG"; exit 1; fi
done

mkdir -p "$ART"

# PREEMPTION: a SIGTERM/SIGINT to this session (host eviction, ^C,
# watcher teardown) forwards to the running stage so an in-flight
# cluster run stops at a safe checkpoint boundary (exit 75, resumable
# with --resume) instead of being orphaned mid-write. The session then
# writes a partial summary naming what completed before exiting 75
# itself — an interrupted capture dir is still a readable artifact.
STAGE_PID=
CURRENT_STAGE=
COMPLETED_STAGES=
INTERRUPTED=
on_signal() {
  INTERRUPTED=$1
  echo "=== $1 received $(date -u) — forwarding to stage" \
       "'${CURRENT_STAGE:-none}' ===" >> "$LOG"
  if [ -n "$STAGE_PID" ] && kill -0 "$STAGE_PID" 2>/dev/null; then
    # `timeout` relays the signal to its child's process group, so
    # every tunnel-using descendant (pytest, bench, chaos subprocesses)
    # sees it and can stop cooperatively
    kill -TERM "$STAGE_PID" 2>/dev/null
  fi
}
trap 'on_signal SIGTERM' TERM
trap 'on_signal SIGINT' INT
partial_summary() {
  { echo "=== PARTIAL SESSION (interrupted by $INTERRUPTED) $(date -u) ==="
    echo "completed stages:${COMPLETED_STAGES:- none}"
    echo "interrupted stage: ${CURRENT_STAGE:-none}"
    echo "resume: rerun this script; checkpointed stages continue"
  } | tee -a "$LOG" > "$ART/partial_summary.txt"
}

run_stage() {  # run_stage <name> <timeout> <cmd...>
  local name=$1 tmo=$2; shift 2
  CURRENT_STAGE=$name
  echo "--- $name $(date -u) ---" >> "$LOG"
  # Every stage gets a run-report sink (galah_tpu/obs); obs-aware
  # stages (bench, cluster-driving scripts) archive their telemetry
  # next to their capture so sessions are diffable with
  # `galah-tpu report --diff`.
  local report="$ART/${name}_report.json"
  echo "=== $name $(date -u) ===" > "$ART/$name.txt"
  # Background + `wait` (not foreground) so the TERM/INT traps can run
  # while the stage is in flight and forward the signal to it.
  timeout -k 10 "$tmo" env GALAH_OBS_REPORT="$report" "$@" \
    >> "$ART/$name.txt" 2>&1 &
  STAGE_PID=$!
  wait "$STAGE_PID"
  local rc=$?
  if [ -n "$INTERRUPTED" ]; then
    # a trap interrupts the first `wait`; this one collects the
    # stage's real (cooperative) exit before we summarize
    wait "$STAGE_PID" 2>/dev/null
    echo "--- interrupted ($INTERRUPTED) $(date -u) ---" >> "$ART/$name.txt"
    cat "$ART/$name.txt" >> "$LOG"
    partial_summary
    exit 75
  fi
  STAGE_PID=
  echo "--- exit $rc $(date -u) ---" >> "$ART/$name.txt"
  cat "$ART/$name.txt" >> "$LOG"
  COMPLETED_STAGES="$COMPLETED_STAGES $name"
  # Soft failure: a missing report degrades observability, not the
  # session — warn and keep going (a hard exit here would throw away
  # the remaining hardware stages over telemetry).
  if [ ! -s "$report" ]; then
    echo "WARN: stage $name produced no run report at $report" >> "$LOG"
  fi
}

# One variable governs both the harness kill and bench.py's internal
# per-stage cap. The internal cap runs 120 s shorter so bench.py can
# skip remaining stages and still print its JSON result line before
# the external `timeout` would SIGKILL it mid-write (the round-5
# captures that exited 124 with no data died exactly that way).
BENCH_TIMEOUT=3000
# Cheap static gate first: kernel contracts, tracer leaks, flag
# registry, shape snapshots, and the GL11xx interprocedural effect
# auditors — seconds on the host VM, and a failure here means the
# expensive hardware stages would exercise broken code. The IR cache
# persists across sessions under the artifact root's parent, so every
# run after the first pays the warm (IR-cached) cost only.
IR_CACHE="${GALAH_TPU_IR_CACHE:-$(dirname "$ART")/lint_ir_cache}"
run_stage lint 300 python -u -m galah_tpu.analysis --json \
  --ir-cache-dir "$IR_CACHE"
# The effects stage token re-runs the GL11xx family in isolation
# against the now-warm IR cache: a hardware session records, in its
# own artifact trail, that the interprocedural contracts (device-round
# sync-freedom, durable-write routing, stage-token adoption) held for
# exactly the tree it benchmarked.
run_stage effects 120 python -u -m galah_tpu.analysis --json \
  --check effects --ir-cache-dir "$IR_CACHE"
# GalahSan smoke on the host CPU: the sanitizer reproducer suite plus
# the lock-heavy obs tests under GALAH_SAN=1 (docs/sanitizer.md). A
# lock-order or GUARDED_BY violation fails here in seconds rather than
# as a flaky hang deep inside a hardware stage.
run_stage san_smoke 600 env JAX_PLATFORMS=cpu \
  bash scripts/lint_gate.sh --san
# Kill-anywhere chaos smoke on the host CPU (no tunnel use): seeded
# interrupted-then-resumed cluster runs must produce byte-identical
# results with zero corrupt artifacts (docs/resilience.md). Runs early
# so a durability regression is caught before the long TPU stages
# depend on checkpoint/resume behaving.
run_stage chaos_smoke 900 env JAX_PLATFORMS=cpu \
  python -u scripts/chaos_run.py --iterations 10 --seed 1
# Same kill/resume gate with the overlapped dataflow forced on (finch
# precluster + GALAH_TPU_OVERLAP=1): kills land inside the fused
# pipeline and the resumed clusters must still be byte-identical.
run_stage chaos_overlap 900 env JAX_PLATFORMS=cpu \
  python -u scripts/chaos_run.py --iterations 6 --seed 2 \
  --workload cluster-overlap
# Elastic-fleet chaos gate: sharded multi-worker runs with SIGKILLed
# worker groups AND a SIGKILLed/SIGTERMed scheduler, resumed from the
# event log, must converge byte-identically to the single-process
# reference with zero tmp debris and a coherent reassignment chain in
# the run report's fleet section (docs/resilience.md).
run_stage chaos_fleet 900 env JAX_PLATFORMS=cpu \
  python -u scripts/chaos_run.py --iterations 10 --seed 3 \
  --workload fleet
# Fleet observability plane (host CPU, no tunnel use): one small
# sharded run with the OpenMetrics textfile exporter on, then `fleet
# analyze` (blame table conserving the fleet wall), `top --json` (the
# per-shard grid), and a Prometheus-parser check of the exported
# .prom (docs/observability.md). Soft-warn: a telemetry regression is
# reported in the capture without discarding the hardware stages.
run_stage fleet_observe 600 bash -c \
  "python -u scripts/fleet_observe.py \
   || echo 'fleet_observe: WARN fleet observability gate failed (soft)'"
run_stage test_tpu_hw 2400 env GALAH_RUN_SLOW=1 \
  python -u -m pytest tests/test_tpu_hw.py -q
run_stage amortized 1800 python -u scripts/bench_amortized.py
# Exact-stage strategy matrix next to the amortized capture: fragment
# kernel pack sweep + xla/C baselines (pairlist's matrix runs inside
# bench.py; this one also runs there, but a dedicated stage survives a
# bench.py wedge and lands in its own artifact).
run_stage fragment_variants 600 python -u scripts/bench_fragment_variants.py
run_stage bench "$BENCH_TIMEOUT" env \
  GALAH_BENCH_STAGE_CAP=$((BENCH_TIMEOUT - 120)) python -u bench.py
# Device-vs-host greedy selection on the synthetic 1000-genome
# planted-family workload: parity gate + genomes/s for both strategies
# (also runs inside bench.py; the dedicated stage survives a bench.py
# wedge and lands in its own artifact).
run_stage engine_rounds 900 python -u scripts/bench_engine_rounds.py \
  --budget 840
# Stage-serial vs fully overlapped end-to-end dataflow on the same
# 1000-genome rung: parity gate + genomes/s for both schedules, the
# overlap counters, and the per-stage pipeline-occupancy gauges (also
# runs inside bench.py; the dedicated stage survives a bench.py wedge
# and lands in its own artifact).
run_stage e2e_overlap 900 python -u scripts/bench_overlap.py \
  --budget 840
# Fused megakernel rounds vs per-window dense folds on the same rung:
# cluster-parity gate, the off/mega greedy-select dispatch ratio
# (acceptance: >= 4x), and the critical path's host-blame share — the
# gauge the fused rounds exist to drive down (<10% target once device
# math dominates; read against host_cores on CPU hosts). Also runs
# inside bench.py; the dedicated stage survives a bench.py wedge.
run_stage megakernel 900 python -u scripts/bench_megakernel.py \
  --budget 840
# 1-D vs 2D tiled mesh all-pairs scaling (N in {1k, 5k, 20k}):
# candidate pairs/s for both geometries, the modeled per-row DCN
# bytes and their ratio (the communication-avoiding claim), and the
# HLL cardinality-band prefilter's pruned fraction — pair-set parity
# gated per rung. On real TPU hardware the bigger rungs fit the
# budget; the CPU-sim fallback self-downshifts to the 1k rung.
run_stage allpairs_scale 900 python -u scripts/bench_allpairs_scale.py \
  --budget 840
# Critical-path attribution over the bench stage's run report: which
# stage owns the e2e wall, as per-stage blame shares (jax-free file
# math). Soft-warn: bench_overlap prints its own OVERLAP_JSON flow
# summary either way; a report without flow telemetry (e.g. the stage
# was skipped under budget) degrades observability, not the session.
run_stage flow_analyze 120 bash -c \
  "python -u -m galah_tpu.cli flow analyze '$ART/bench_report.json' \
   || echo 'flow_analyze: WARN no flow telemetry in bench report (soft)'"
# Perf gate right after the bench stages: the newest ledger entries
# (appended by the bench/engine finalizers above) against their
# same-key median±MAD bands. --soft while hardware history is still
# accumulating: regressions are REPORTED in the capture, not yet
# fatal to the session — flip to hard gating once each key carries a
# trustworthy window (docs/observability.md).
run_stage perf_check 120 python -u -m galah_tpu.cli perf check --soft
run_stage kernel_variants 1200 python -u scripts/bench_kernel_variants.py
run_stage sketch_variants 1200 python -u scripts/bench_sketch_variants.py
# Storage-bound ingest->sketch matrix: streamed pipeline (fused
# kernel on TPU) vs the serial-prologue baseline over a >= 1 Gbp
# corpus (also runs inside bench.py; the dedicated stage survives a
# bench.py wedge and lands in its own artifact).
run_stage ingest_variants 600 python -u scripts/bench_ingest.py \
  --variants --budget 480
# Out-of-core sketch tier vs all-resident: peak-RSS ratio, ingest
# rate per rung, pair-dict parity (docs/memory.md). Also runs inside
# bench.py; same wedge-survival rationale.
run_stage ingest_tiered 600 python -u scripts/bench_ingest_tiered.py \
  --budget 480
# Incremental-index service: build-once then insert-10% throughput
# and the warm query-latency sweep (acceptance: p50 < 50 ms on CPU;
# the TPU capture records the same numbers under the device sketch
# path). Also runs inside bench.py; same wedge-survival rationale.
run_stage index_service 300 python -u scripts/bench_index.py \
  --budget 240
run_stage ladder_tpu 3600 python -u scripts/ladder_bench.py --n 1000 \
  --genome-len 100000 --skip-rung1 --hash tpufast --ani-subsample 16

echo "=== done $(date -u) — captures in $ART ===" >> "$LOG"
