#!/bin/bash
# Opportunistic TPU validation: wait for a responsive tunnel, then run
# the hardware kernel validation, the benchmark, and the TPU ladder in
# sequence, logging everything to scripts/tpu_validation.log.
set -u
LOG=/root/repo/scripts/tpu_validation.log
cd /root/repo
echo "=== tpu_validation_run $(date -u) ===" >> "$LOG"

for attempt in $(seq 1 60); do
  t0=$(date +%s)
  if timeout -k 5 90 python -c "import jax; jax.devices()" 2>/dev/null; then
    dt=$(( $(date +%s) - t0 ))
    echo "probe ok in ${dt}s (attempt $attempt) $(date -u)" >> "$LOG"
    break
  fi
  echo "probe failed (attempt $attempt) $(date -u)" >> "$LOG"
  sleep 120
  if [ "$attempt" = 60 ]; then echo "giving up" >> "$LOG"; exit 1; fi
done

echo "--- test_tpu_hw ---" >> "$LOG"
timeout 2400 python -m pytest tests/test_tpu_hw.py -q >> "$LOG" 2>&1

echo "--- bench.py ---" >> "$LOG"
timeout 1800 python bench.py >> "$LOG" 2>/dev/null

echo "--- sketch variants ---" >> "$LOG"
timeout 1200 python scripts/bench_sketch_variants.py >> "$LOG" 2>&1

echo "--- pair-stats kernel variants ---" >> "$LOG"
timeout 1200 python scripts/bench_kernel_variants.py >> "$LOG" 2>&1

echo "--- ladder (tpu, tpufast c=16) ---" >> "$LOG"
timeout 2400 python scripts/ladder_bench.py --n 100 \
  --genome-len 300000 --skip-rung1 --hash tpufast \
  --ani-subsample 16 >> "$LOG" 2>/dev/null

echo "=== done $(date -u) ===" >> "$LOG"
