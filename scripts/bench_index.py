"""Incremental-index bench: build once, insert 10%, sweep query latency.

The index subsystem's value claim is that growing a dereplicated
catalogue costs the marginal work, not the from-scratch work: an
insert sketches ONLY the new genomes, and a query answers from the
committed state in milliseconds. This bench measures both sides on a
planted-family corpus:

  1. ``build`` over 90% of the corpus (the device sketch pipeline +
     persisted decisions) — amortized once per catalogue;
  2. ``insert`` of the remaining 10% — wall seconds, genomes/s, and
     the ``sketch.minhash_computed`` counter delta proving only the
     new genomes were resketched;
  3. a warm ``query`` latency sweep (every inserted genome against the
     committed state) — p50/p95 milliseconds per genome, the
     interactive-service number (acceptance: warm p50 < 50 ms on CPU).

Usage: python scripts/bench_index.py [--families 16] [--members 5]
       [--length 20000] [--queries 0 (= all inserted)] [--budget S]
Prints one JSON line per measurement and INDEX_JSON with the summary.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _percentile(values, q):
    if not values:
        return None
    vs = sorted(values)
    i = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
    return vs[i]


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--families", type=int, default=16)
    ap.add_argument("--members", type=int, default=5)
    ap.add_argument("--length", type=int, default=20_000)
    ap.add_argument("--queries", type=int, default=0,
                    help="query sweep size (0 = every inserted genome)")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--budget", type=float, default=0.0,
                    help="soft self-budget in seconds (skips the query "
                         "sweep when the build+insert already spent it)")
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()
    t_start = time.perf_counter()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from scripts.chaos_run import make_workload

    from galah_tpu.index import incremental
    from galah_tpu.index.store import IndexStore
    from galah_tpu.obs import metrics as obs_metrics

    work = tempfile.mkdtemp(prefix="galah_bench_index_")
    out = {"n_genomes": args.families * args.members}
    try:
        gdir = os.path.join(work, "genomes")
        os.makedirs(gdir)
        genomes = make_workload(gdir, seed=7, families=args.families,
                                members=args.members,
                                length=args.length)
        # the insert slice is ~10%: the last member of every ~10th
        # family joins an existing cluster, one whole held-out family
        # founds a new one — both decision paths under measurement
        insert = genomes[-args.members:] \
            + genomes[args.members - 1:-args.members:args.members * 10]
        base = [g for g in genomes if g not in insert]
        out["n_build"] = len(base)
        out["n_insert"] = len(insert)
        cache = os.path.join(work, "cache")
        idx_dir = os.path.join(work, "idx")

        t0 = time.perf_counter()
        info = incremental.build(idx_dir, base, ani=0.95,
                                 precluster_ani=0.90, cache_dir=cache,
                                 threads=args.threads)
        out["build_s"] = round(time.perf_counter() - t0, 3)
        out["build_genomes_per_sec"] = round(
            len(base) / max(out["build_s"], 1e-9), 2)
        out["build_clusters"] = info["clusters"]
        print(json.dumps({"stage": "build", **{
            k: out[k] for k in ("n_build", "build_s",
                                "build_clusters")}}), flush=True)

        def _resketched():
            snap = obs_metrics.snapshot().get("sketch.minhash_computed")
            return int(snap.get("value", 0)) if snap else 0

        idx = IndexStore(idx_dir)
        before = _resketched()
        t0 = time.perf_counter()
        info = incremental.insert(idx, insert, cache_dir=cache,
                                  threads=args.threads)
        out["insert_s"] = round(time.perf_counter() - t0, 3)
        out["insert_genomes_per_sec"] = round(
            len(insert) / max(out["insert_s"], 1e-9), 2)
        out["insert_resketched"] = _resketched() - before
        out["insert_new_reps"] = info.get("new_reps", 0)
        out["clusters"] = info["clusters"]
        print(json.dumps({"stage": "insert", **{
            k: out[k] for k in ("n_insert", "insert_s",
                                "insert_resketched",
                                "insert_new_reps")}}), flush=True)
        if out["insert_resketched"] > len(insert):
            out["error"] = (
                f"insert resketched {out['insert_resketched']} "
                f"genomes, expected <= {len(insert)}")

        spent = time.perf_counter() - t_start
        if args.budget and spent > args.budget:
            print(f"budget spent ({spent:.0f}s); skipping query sweep",
                  flush=True)
        else:
            qpaths = insert if not args.queries \
                else insert[:args.queries]
            # warm the query path once (sketches are cache-hits after
            # the insert; the first call pays one-time imports)
            incremental.query(idx, qpaths[:1], cache_dir=cache,
                              threads=args.threads)
            lat_ms = []
            for p in qpaths:
                t0 = time.perf_counter()
                incremental.query(idx, [p], cache_dir=cache,
                                  threads=args.threads)
                lat_ms.append((time.perf_counter() - t0) * 1e3)
            out["query_n"] = len(lat_ms)
            out["query_p50_ms"] = round(_percentile(lat_ms, 0.50), 3)
            out["query_p95_ms"] = round(_percentile(lat_ms, 0.95), 3)
            print(json.dumps({"stage": "query", **{
                k: out[k] for k in ("query_n", "query_p50_ms",
                                    "query_p95_ms")}}), flush=True)
    finally:
        if args.keep:
            print(f"kept scratch: {work}", flush=True)
        else:
            shutil.rmtree(work, ignore_errors=True)
    print("INDEX_JSON " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
