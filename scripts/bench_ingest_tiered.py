"""Tiered (out-of-core) sketch store vs all-resident: peak RSS and
ingest rate at N in {1k, 20k, 100k} synthetic genomes.

The tentpole claim of the memory hierarchy (docs/memory.md) is that
the paged band walk completes the same workload with a peak RSS bound
by the pagestore budget instead of the corpus size, bit-identically.
Each (rung, paging on/off) variant runs in its own subprocess so
``ru_maxrss`` is a clean per-variant high-water mark:

  * ingest: N synthetic planted-family sketch rows stream into either
    an all-resident ``(N, K)`` u64 matrix (paging off — the resident
    cost IS the matrix) or a ``SketchPageStore`` under a 16 MiB
    budget (paging on — rows page out as they arrive);
  * pair pass: the bucketed band walk over the first
    ``min(N, PARITY_ROWS)`` rows, paged vs dense — the sha256 digest
    of the pair dict is the parity gate (identical planted rows +
    identical cards => must match bit for bit).

Self-budgeting: rungs are admitted in order while the measured wall
extrapolates into ``--budget``; skipped rungs are recorded, never
silently dropped. Prints one JSON line per variant and a final
``TIERED_JSON`` summary (bench.py flattens its ``pagestore_*`` keys
into the perf ledger's ``bench.pagestore_*`` gauges).

Usage: python scripts/bench_ingest_tiered.py [--budget 480]
       [--rungs 1000,20000,100000] [--width 1000]
"""

import argparse
import hashlib
import json
import os
import resource
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Rows entering the bucketed pair-pass parity gate per rung — the
#: RSS story is carried by ingest; the pair pass is capped so CPU
#: rungs stay inside the stage budget.
PARITY_ROWS = 2048
FAMILY = 4            # planted family size (members per base row)
MUTATIONS = 3         # mutated slots per non-base member
PAGED_BUDGET_MB = 16  # pagestore resident budget for the paging-on arm


def _maxrss_mb() -> float:
    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # linux reports KiB, macOS bytes
    return ru / 1024.0 if sys.platform != "darwin" else ru / (1 << 20)


def _make_chunk(rng, lo, hi, width, bases):
    """Rows [lo, hi) of the planted-family corpus: row i belongs to
    family i // FAMILY; non-base members mutate MUTATIONS slots of the
    family base row. Deterministic in (seed, chunking is per-family)."""
    import numpy as np

    out = np.empty((hi - lo, width), dtype=np.uint64)
    for i in range(lo, hi):
        fam, member = divmod(i, FAMILY)
        base = bases(fam)
        row = base.copy()
        if member:
            mrng = np.random.default_rng(hash((fam, member)) & 0x7FFFFFFF)
            idx = mrng.choice(width, size=MUTATIONS, replace=False)
            row[idx] = mrng.integers(0, 1 << 62, size=MUTATIONS,
                                     dtype=np.uint64)
        row.sort()
        out[i - lo] = row
    return out


def _cards(n):
    """Per-row HLL cardinality stand-ins, family-correlated so the
    band partition is non-trivial; identical in both arms."""
    import numpy as np

    fam = np.arange(n) // FAMILY
    return (5_000.0 + 137.0 * (fam % 97)).astype(np.float64)


def run_child(n: int, paging: bool, width: int, seed: int) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from galah_tpu.ops.bucketing import bucketed_threshold_pairs

    base_cache: dict = {}

    def bases(fam):
        if fam not in base_cache:
            if len(base_cache) > 64:
                base_cache.clear()
            frng = np.random.default_rng(seed * 1_000_003 + fam)
            base_cache[fam] = frng.integers(0, 1 << 62, size=width,
                                            dtype=np.uint64)
        return base_cache[fam]

    # Warm the pair machinery BEFORE the RSS baseline so delta_rss_mb
    # measures the corpus + pass, not one-time import cost. The real
    # pass runs PARITY_ROWS >= the sparse-screen crossover, whose
    # jax/jit imports dominate a cold process's footprint — warm with
    # a small matrix on the same side of the crossover.
    from galah_tpu.ops.collision import sparse_screen_min_n

    wn = max(8, sparse_screen_min_n()) if PARITY_ROWS >= \
        sparse_screen_min_n() else 8
    wrng = np.random.default_rng(1)
    warm = wrng.integers(0, 1 << 62, size=(wn, width), dtype=np.uint64)
    warm.sort(axis=1)
    bucketed_threshold_pairs(warm, _cards(wn), k=21, min_ani=0.95,
                             sketch_size=width)
    del warm
    rss0 = _maxrss_mb()
    rng = np.random.default_rng(seed)
    chunk = 1024
    t0 = time.perf_counter()
    page_ins = page_outs = resident = 0
    if paging:
        import shutil
        import tempfile

        from galah_tpu.io.pagestore import SketchPageStore

        d = tempfile.mkdtemp(prefix="bench-pagestore-")
        store = SketchPageStore(
            d, cols=width, budget_bytes=PAGED_BUDGET_MB << 20)
        for lo in range(0, n, chunk):
            rows = _make_chunk(rng, lo, min(lo + chunk, n), width, bases)
            for j in range(rows.shape[0]):
                store.append(f"g{lo + j}", rows[j])
        store.flush()
        ingest_s = time.perf_counter() - t0
        m = min(n, PARITY_ROWS)
        from galah_tpu.io.pagestore import PagedRowView

        mat = PagedRowView(store, np.arange(m))
    else:
        full = np.empty((n, width), dtype=np.uint64)
        for lo in range(0, n, chunk):
            full[lo:min(lo + chunk, n)] = _make_chunk(
                rng, lo, min(lo + chunk, n), width, bases)
        ingest_s = time.perf_counter() - t0
        m = min(n, PARITY_ROWS)
        mat = full[:m]

    t1 = time.perf_counter()
    pairs = bucketed_threshold_pairs(
        mat, _cards(m), k=21, min_ani=0.95, sketch_size=width)
    pair_s = time.perf_counter() - t1
    if paging:
        page_ins = store._c_page_ins.value
        page_outs = store._c_page_outs.value
        resident = store.resident_bytes
        store.close()
        shutil.rmtree(d, ignore_errors=True)

    digest = hashlib.sha256(json.dumps(
        sorted((i, j, round(float(a), 12))
               for (i, j), a in pairs.items())).encode()).hexdigest()
    print("CHILD_JSON " + json.dumps({
        "n": n, "paging": paging,
        "peak_rss_mb": round(_maxrss_mb(), 1),
        "baseline_rss_mb": round(rss0, 1),
        "delta_rss_mb": round(_maxrss_mb() - rss0, 1),
        "ingest_s": round(ingest_s, 2),
        "genomes_per_sec": round(n / max(ingest_s, 1e-9), 1),
        "pair_s": round(pair_s, 2),
        "parity_rows": m, "n_pairs": len(pairs),
        "pairs_digest": digest,
        "page_ins": page_ins, "page_outs": page_outs,
        "resident_bytes": resident,
    }), flush=True)


def _spawn(n, paging, width, seed, timeout):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", str(n),
         "--paging", "on" if paging else "off",
         "--width", str(width), "--seed", str(seed)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env)
    for line in proc.stdout.splitlines():
        if line.startswith("CHILD_JSON "):
            return json.loads(line[len("CHILD_JSON "):])
    raise RuntimeError(f"rung n={n} paging={paging} rc={proc.returncode}: "
                       f"{proc.stderr[-500:]}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--budget", type=float, default=480.0,
                    help="soft wall-clock budget in seconds")
    ap.add_argument("--rungs", default="1000,20000,100000")
    ap.add_argument("--width", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--child", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--paging", default="off", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child is not None:
        run_child(args.child, args.paging == "on", args.width, args.seed)
        return 0

    t0 = time.monotonic()
    rungs = [int(x) for x in args.rungs.split(",") if x]
    out = {"rungs": {}, "skipped": [], "parity_ok": True}
    # Cost model: per-arm wall = fixed (imports + capped pair pass)
    # + ingest, with only the ingest part scaling in n.
    fixed_s, ingest_s, last_n = 20.0, 5.0, rungs[0]
    for n in rungs:
        est = 2 * (fixed_s + ingest_s * max(1.0, n / last_n)) * 1.5
        rem = args.budget - (time.monotonic() - t0)
        if est > rem:
            out["skipped"].append(
                {"n": n, "reason": f"est {est:.0f}s > {rem:.0f}s left"})
            continue
        t1 = time.monotonic()
        off = _spawn(n, False, args.width, args.seed, timeout=rem)
        on = _spawn(n, True, args.width, args.seed,
                    timeout=max(args.budget - (time.monotonic() - t0),
                                30.0))
        arm_wall = (time.monotonic() - t1) / 2
        ingest_s = max((off["ingest_s"] + on["ingest_s"]) / 2, 0.5)
        fixed_s = max(arm_wall - ingest_s, 1.0)
        last_n = n
        parity = off["pairs_digest"] == on["pairs_digest"]
        out["parity_ok"] = out["parity_ok"] and parity
        ratio = (on["delta_rss_mb"] / off["delta_rss_mb"]
                 if off["delta_rss_mb"] > 0 else None)
        rung = {"resident": off, "paged": on, "parity": parity,
                "delta_rss_ratio": (round(ratio, 3)
                                    if ratio is not None else None)}
        out["rungs"][str(n)] = rung
        print(json.dumps({"rung": n, "parity": parity,
                          "delta_rss_ratio": rung["delta_rss_ratio"],
                          "paged_genomes_per_sec": on["genomes_per_sec"],
                          "resident_genomes_per_sec":
                              off["genomes_per_sec"]}), flush=True)

    done = [int(k) for k in out["rungs"]]
    if done:
        big = str(max(done))
        r = out["rungs"][big]
        out["headline_n"] = int(big)
        # the perf-ledger gauges (bench.pagestore_*): RSS ratio of the
        # paged arm over all-resident, both arms' ingest rates, and
        # the paging traffic that bought the bound
        out["pagestore_delta_rss_ratio"] = r["delta_rss_ratio"]
        out["pagestore_paged_genomes_per_sec"] = \
            r["paged"]["genomes_per_sec"]
        out["pagestore_resident_genomes_per_sec"] = \
            r["resident"]["genomes_per_sec"]
        out["pagestore_page_ins"] = r["paged"]["page_ins"]
        out["pagestore_page_outs"] = r["paged"]["page_outs"]
        out["pagestore_parity_ok"] = int(out["parity_ok"])
    print("TIERED_JSON " + json.dumps(out), flush=True)
    return 0 if out["parity_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
