"""Amortized ON-CHIP kernel throughput: the MFU measurement campaign.

Every prior TPU number was captured through the axon tunnel, where a
single dispatch pays 50-150 ms of RTT plus transfer — so per-dispatch
timings are lower bounds that conflate kernel speed with tunnel
overhead. This script separates them: inputs are uploaded ONCE and
stay device-resident, the kernel runs `reps` times inside ONE jitted
`lax.fori_loop` dispatch (with `lax.optimization_barrier` on the
inputs each iteration so XLA cannot hoist the loop-invariant call),
and the per-iteration time comes from the slope between two rep
counts — subtracting the single dispatch+RTT constant exactly.

Reports, per kernel family (dense pair-stats tile, pairlist, murmur3
sketch core Mosaic AND XLA-emulated): amortized work/s, the implied
dispatch overhead, and achieved % of the self-derived VPU roofline
from BASELINE.md (~800k pairs/s/chip for the O(K_pad^2) pair kernels
at K=1000, ~9 G k-mer/s for the murmur core). These are the numbers
that replace BASELINE.md's "should sit near the compute roofline
on-chip" extrapolation with a measurement.

Hoist guard: if total time fails to grow ~linearly in reps the
optimization barrier did not hold and the row is marked SUSPECT
instead of being reported as a (bogus) super-roofline number.

Reference contract being measured against: the compiled dense pair
loop the reference runs on host (reference: src/finch.rs:53-73).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Self-derived VPU ceilings (BASELINE.md roofline section): ~6e12 u32
# ops/s per v5e chip; ~7.3M u32 ops per pair at K_pad=1024 for the
# O(K_pad^2) compare kernels; ~650 u32 ops per k-mer for murmur3.
PAIR_CEILING = 800_000.0      # pairs/s/chip, K=1000
SKETCH_CEILING = 9.0e9        # k-mers/s/chip


def _measure_amortized(make_fn, reps_lo, reps_hi, repeats=2):
    """Per-iteration seconds from the slope between two rep counts.

    make_fn(reps) -> zero-arg callable returning a scalar (host
    materialization forces completion; through the tunnel
    block_until_ready is async). Returns (per_iter_s, dispatch_s,
    suspect, drift_ok)."""
    f_lo, f_hi = make_fn(reps_lo), make_fn(reps_hi)
    ref_lo, ref_hi = f_lo(), f_hi()   # compile + warm both rep counts

    def best_of(f, expect):
        best, drift = float("inf"), True
        for _ in range(repeats):
            t0 = time.perf_counter()
            got = f()
            best = min(best, time.perf_counter() - t0)
            drift &= (got == expect)
        return best, drift

    t_lo, ok_lo = best_of(f_lo, ref_lo)
    t_hi, ok_hi = best_of(f_hi, ref_hi)
    per_iter = (t_hi - t_lo) / (reps_hi - reps_lo)
    dispatch = t_lo - reps_lo * per_iter
    # linearity guard: reps_hi/reps_lo >= 4 must show real growth
    suspect = t_hi < 1.5 * t_lo or per_iter <= 0
    return per_iter, max(dispatch, 0.0), suspect, ok_lo and ok_hi


def _row(label, work_per_iter, per_iter, dispatch, suspect, drift_ok,
         ceiling, out):
    rate = work_per_iter / per_iter if per_iter > 0 else 0.0
    pct = 100.0 * rate / ceiling if ceiling else None
    flag = " SUSPECT-HOIST" if suspect else ""
    flag += "" if drift_ok else " DRIFT"
    print(f"{label}: {rate:,.0f} /s amortized "
          f"({per_iter*1e3:.2f} ms/iter, dispatch {dispatch*1e3:.0f} ms"
          + (f", {pct:.1f}% of ceiling" if pct is not None else "")
          + f"){flag}", flush=True)
    out[label] = {
        "rate_per_s": round(rate, 1),
        "per_iter_ms": round(per_iter * 1e3, 3),
        "dispatch_ms": round(dispatch * 1e3, 1),
        "pct_of_ceiling": round(pct, 2) if pct is not None else None,
        "suspect": bool(suspect),
        "drift_ok": bool(drift_ok),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interpret", action="store_true",
                    help="CPU smoke mode: tiny shapes, interpret=True")
    ap.add_argument("--fast", action="store_true",
                    help="bench.py stage mode: skip the range_skip "
                         "variant (fewer tunnel compiles); the watcher "
                         "captures the full matrix separately")
    args = ap.parse_args()

    import jax

    interpret = args.interpret
    if interpret:
        # CPU smoke must not touch the (possibly wedged) TPU tunnel;
        # the env var alone is overridden by the axon sitecustomize
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from galah_tpu.ops.pallas_pairlist import pair_stats_pairs_pallas
    from galah_tpu.ops.pallas_pairwise import tile_stats_pallas

    if not interpret:
        assert jax.default_backend() == "tpu", jax.default_backend()

    K = 1000
    rng = np.random.default_rng(1)
    results = {}

    def dev(x):
        return jax.device_put(jnp.asarray(x))

    # --- dense pair-stats tile kernel (and range_skip variant) ---
    n = 64 if interpret else 512
    m = rng.integers(0, 1 << 63, size=(2 * n, K), dtype=np.uint64)
    m.sort(axis=1)
    r_d, c_d = dev(m[:n]), dev(m[n:])

    def make_tile(range_skip):
        def make_fn(reps):
            @jax.jit
            def run():
                def body(_, acc):
                    rr, cc = jax.lax.optimization_barrier((r_d, c_d))
                    cm, tt = tile_stats_pallas(
                        rr, cc, K, interpret=interpret,
                        range_skip=range_skip)
                    return acc + jnp.sum(cm, dtype=jnp.int32) \
                        + jnp.sum(tt, dtype=jnp.int32)
                return jax.lax.fori_loop(
                    0, reps, body, jnp.int32(0), unroll=False)
            return lambda: int(np.asarray(run()))
        return make_fn

    lo, hi = (1, 3) if interpret else (1, 6)
    for skip in ((False,) if args.fast else (False, True)):
        label = f"dense-tile {n}x{n}" + ("+skip" if skip else "")
        per, disp, sus, ok = _measure_amortized(make_tile(skip), lo, hi)
        _row(label, n * n, per, disp, sus, ok, PAIR_CEILING, results)

    # --- pairlist kernel (the sparse production path's exact pass) ---
    b = 256 if interpret else 8192
    pool = rng.integers(0, 1 << 63, size=(1024, K), dtype=np.uint64)
    pool.sort(axis=1)
    pa = dev(pool[rng.integers(0, 1024, size=b)])
    pb = dev(pool[rng.integers(0, 1024, size=b)])

    def make_pairlist(range_skip, block_pairs=None):
        def make_fn(reps):
            @jax.jit
            def run():
                def body(_, acc):
                    aa, bb = jax.lax.optimization_barrier((pa, pb))
                    cm, tt = pair_stats_pairs_pallas(
                        aa, bb, K, interpret=interpret,
                        range_skip=range_skip,
                        block_pairs=block_pairs)
                    return acc + jnp.sum(cm, dtype=jnp.int32) \
                        + jnp.sum(tt, dtype=jnp.int32)
                return jax.lax.fori_loop(
                    0, reps, body, jnp.int32(0), unroll=False)
            return lambda: int(np.asarray(run()))
        return make_fn

    from galah_tpu.ops.pallas_pairlist import pairlist_block_pairs

    P = pairlist_block_pairs()
    # blocked production default, plus the retired one-pair grid as
    # the A/B baseline (the round-5 62.8k pairs/s configuration)
    variants = [(False, P, f"pairlist B={b} P={P}"),
                (False, 1, f"pairlist B={b} P=1")]
    if not args.fast:
        variants.append((True, 1, f"pairlist B={b} P=1+skip"))
    for skip, bp, label in variants:
        per, disp, sus, ok = _measure_amortized(
            make_pairlist(skip, block_pairs=bp),
            *((1, 3) if interpret else (1, 6)))
        _row(label, b, per, disp, sus, ok, PAIR_CEILING, results)

    # --- murmur3 sketch core: Mosaic kernel vs XLA u64 emulation ---
    from galah_tpu.ops.hashing import _murmur3_k21_1d
    from galah_tpu.ops.pallas_sketch import murmur3_k21_pallas

    nk = (1 << 16) if interpret else (1 << 21)
    kw = [dev(rng.integers(0, 1 << 64, size=nk, dtype=np.uint64))
          for _ in range(3)]

    def make_mosaic(reps):
        @jax.jit
        def run():
            def body(_, acc):
                k1, k2, t = jax.lax.optimization_barrier(tuple(kw))
                h = murmur3_k21_pallas(k1, k2, t, seed=0,
                                       interpret=interpret)
                return acc + jnp.sum(
                    h.astype(jnp.uint32).astype(jnp.int32),
                    dtype=jnp.int32)
            return jax.lax.fori_loop(
                0, reps, body, jnp.int32(0), unroll=False)
        return lambda: int(np.asarray(run()))

    def make_xla(reps):
        @jax.jit
        def run():
            def body(_, acc):
                k1, k2, t = jax.lax.optimization_barrier(tuple(kw))
                cb = [(k1 >> jnp.uint64(8 * bb)) & jnp.uint64(0xFF)
                      for bb in range(8)]
                cb += [(k2 >> jnp.uint64(8 * bb)) & jnp.uint64(0xFF)
                       for bb in range(8)]
                cb += [(t >> jnp.uint64(8 * bb)) & jnp.uint64(0xFF)
                       for bb in range(5)]
                h = _murmur3_k21_1d(cb, 0)
                return acc + jnp.sum(
                    h.astype(jnp.uint32).astype(jnp.int32),
                    dtype=jnp.int32)
            return jax.lax.fori_loop(
                0, reps, body, jnp.int32(0), unroll=False)
        return lambda: int(np.asarray(run()))

    lo, hi = (1, 3) if interpret else (2, 16)
    per, disp, sus, ok = _measure_amortized(make_mosaic, lo, hi)
    _row(f"murmur-mosaic n={nk}", nk, per, disp, sus, ok,
         SKETCH_CEILING, results)
    per, disp, sus, ok = _measure_amortized(make_xla, lo, hi)
    _row(f"murmur-xla n={nk}", nk, per, disp, sus, ok,
         SKETCH_CEILING, results)

    mos = results.get(f"murmur-mosaic n={nk}", {})
    xla = results.get(f"murmur-xla n={nk}", {})
    if mos.get("rate_per_s") and xla.get("rate_per_s"):
        ratio = mos["rate_per_s"] / xla["rate_per_s"]
        print(f"murmur verdict: Mosaic/XLA = {ratio:.2f}x on-chip "
              f"(default flips to Mosaic if >= 1.1)", flush=True)
        results["murmur_mosaic_over_xla"] = round(ratio, 3)

    print("AMORTIZED_JSON " + json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
