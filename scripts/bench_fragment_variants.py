"""Per-strategy fragment-ANI throughput + packing-waste breakdown.

BASELINE.md's ladder rungs put the exact-ANI refinement at ~half the
end-to-end wall (rung-realistic-1000x3Mbp: 70-73 s of 145 s) with one
XLA searchsorted dispatch per genome pair. This stage prices every
membership strategy (ops/fragment_ani._resolve_fragment_strategy) on
the SAME synthetic pair list and decomposes the Pallas path's cost:

  * pallas P sweep (GALAH_TPU_FRAGMENT_PAIRS = 1 / 8 / unset):
    wall-clock through _directed_ani_batch_pallas — includes host
    planning, packing, and the bincount fold, so it is the rate a
    production run would see; the launch/job/span counters quantify
    dispatch amortization and pow2 padding waste at each P;
  * xla: the per-bucket vmapped-searchsorted path, same wall-clock
    protocol;
  * c merge: the compiled-C host path (skipped without the toolchain);
  * kernel amortized: the bare _window_hits launch on pre-packed
    planes via bench_amortized's slope method — per-launch dispatch
    cost and on-chip element rate with host packing excluded, so
    (wall - kernel) isolates the host-side term.

Self-budgeting like bench_pairlist_variants: variants run in priority
order under a budget (default 300 s; GALAH_BENCH_STAGE_CAP caps it
harder) and a partial run still prints FRAGMENT_JSON with what it
measured and what it skipped.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_amortized import _measure_amortized  # noqa: E402

_T0 = time.monotonic()

# Launch-related counters copied into each pallas row (deltas across
# the timed call), mirroring the pairlist stage's waste counters.
_COUNTERS = ("fragment-pallas-launches", "fragment-pallas-pairs",
             "fragment-pallas-jobs", "fragment-pallas-job-slots",
             "fragment-pallas-ref-blocks",
             "fragment-pallas-ref-blocks-needed")


def _mutate(codes, rate, seed):
    r = np.random.default_rng(seed)
    out = codes.copy()
    mut = r.random(out.shape[0]) < rate
    out[mut] = r.integers(0, 4, size=int(mut.sum())).astype(np.uint8)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interpret", action="store_true",
                    help="CPU smoke mode: tiny shapes, interpret=True")
    ap.add_argument("--budget", type=float, default=None,
                    help="seconds for the whole stage (default 300, "
                         "capped by GALAH_BENCH_STAGE_CAP)")
    args = ap.parse_args()

    budget = args.budget if args.budget is not None else 300.0
    cap = os.environ.get("GALAH_BENCH_STAGE_CAP")
    if cap:
        budget = min(budget, float(cap))

    import jax

    interpret = args.interpret
    if interpret:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from galah_tpu.io.fasta import Genome, GenomeStats
    from galah_tpu.ops import fragment_ani as fa
    from galah_tpu.ops import pallas_fragment as pf
    from galah_tpu.utils import timing

    if not interpret:
        assert jax.default_backend() == "tpu", jax.default_backend()

    # Interpret mode is a wiring smoke, not a measurement: small
    # genomes, heavy FracMinHash subsampling, few pairs.
    size = 80_000 if interpret else 3_000_000
    sub_c = 4 if interpret else 125
    n_var = 4 if interpret else 8
    n_pairs = 24 if interpret else 512
    rng = np.random.default_rng(3)
    results = {}
    skipped = []

    def left():
        return budget - (time.monotonic() - _T0)

    def admit(cost_s, label):
        if left() >= cost_s:
            return True
        skipped.append(label)
        print(f"SKIP {label}: needs ~{cost_s:.0f}s, "
              f"{left():.0f}s left", flush=True)
        return False

    base = rng.integers(0, 4, size=size).astype(np.uint8)
    offs = np.array([0, size], dtype=np.int64)
    profiles = []
    for i in range(n_var):
        codes = base if i == 0 else _mutate(base, 0.01 * i, 50 + i)
        g = Genome(path=f"bench{i}.fna", codes=codes,
                   contig_offsets=offs.copy(),
                   stats=GenomeStats(1, 0, size))
        profiles.append(fa.build_profile(g, 15, 3000,
                                         subsample_c=sub_c))
    directed = [(profiles[i], profiles[j])
                for i in range(n_var) for j in range(n_var) if i != j]
    pairs = [directed[i % len(directed)] for i in range(n_pairs)]
    # warm the per-profile caches outside any timed region
    for p in profiles:
        p.sorted_query()
        p.padded_ref_set()
        p.padded_windows()

    def wall(fn, label, cost_s, extra=None):
        if not admit(cost_s, label):
            return
        try:
            fn()                       # warmup: compiles + caches
            before = timing.GLOBAL.counters()
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            after = timing.GLOBAL.counters()
            rate = len(pairs) / dt if dt > 0 else 0.0
            row = {"rate_per_s": round(rate, 1),
                   "wall_ms": round(dt * 1e3, 3),
                   "us_per_pair": round(dt * 1e6 / len(pairs), 3),
                   "n_pairs": len(pairs)}
            for c in _COUNTERS:
                d = after.get(c, 0) - before.get(c, 0)
                if d:
                    row[c] = d
            launches = row.get("fragment-pallas-launches")
            if launches:
                row["pairs_per_launch"] = round(
                    len(pairs) / launches, 2)
                slots = row.get("fragment-pallas-job-slots", 0)
                jobs = row.get("fragment-pallas-jobs", 0)
                if slots:
                    row["job_occupancy"] = round(jobs / slots, 4)
                scanned = row.get("fragment-pallas-ref-blocks", 0)
                needed = row.get("fragment-pallas-ref-blocks-needed", 0)
                if scanned:
                    row["span_occupancy"] = round(needed / scanned, 4)
            if extra:
                row.update(extra)
            print(f"{label}: {rate:,.0f} pairs/s wall "
                  f"({row['us_per_pair']} us/pair)", flush=True)
            results[label] = row
        except Exception as e:  # noqa: BLE001 - record, keep going
            print(f"FAIL {label}: {type(e).__name__}: {e}", flush=True)
            results[label] = {"error": f"{type(e).__name__}: {e}"}

    # --- pallas pack sweep: P caps launch packing; unset = auto ---
    c_pal = 60 if interpret else 60
    for p in (1, 8, None):
        label = f"pallas P={'auto' if p is None else p}"

        def run(p=p):
            old = os.environ.pop("GALAH_TPU_FRAGMENT_PAIRS", None)
            if p is not None:
                os.environ["GALAH_TPU_FRAGMENT_PAIRS"] = str(p)
            try:
                fa._directed_ani_batch_pallas(pairs, 0.80, 0.5)
            finally:
                os.environ.pop("GALAH_TPU_FRAGMENT_PAIRS", None)
                if old is not None:
                    os.environ["GALAH_TPU_FRAGMENT_PAIRS"] = old
        wall(run, label, c_pal)

    # --- xla vmapped searchsorted, same protocol ---
    wall(lambda: fa._directed_ani_batch_xla(pairs, 0.80, 0.5),
         "xla vmapped", 60 if interpret else 90)

    # --- compiled-C merge (host path) ---
    if fa._c_merge_available():
        wall(lambda: fa._directed_ani_batch_cmerge(
            pairs, 0.80, 0.5, threads=1), "c merge", 30)
    else:
        skipped.append("c merge (no toolchain)")

    # --- bare kernel, amortized slope: dispatch cost + on-chip rate
    # on pre-packed planes (host packing excluded) ---
    label = "kernel amortized"
    if admit(60 if interpret else 45, label):
        try:
            jobs, span = (8, 2)
            qb = pf.A_SUB * pf.QLA
            rb = pf.RSB * pf.B_LANE
            q = np.sort(rng.integers(
                0, 1 << 63, size=jobs * qb, dtype=np.uint64))
            q_hi = jax.device_put(jnp.asarray(
                (q >> np.uint64(32)).astype(np.uint32).reshape(
                    jobs, pf.QLA, pf.A_SUB).transpose(0, 2, 1).reshape(
                    jobs * pf.A_SUB, pf.QLA)))
            q_lo = jax.device_put(jnp.asarray(
                q.astype(np.uint32).reshape(
                    jobs, pf.QLA, pf.A_SUB).transpose(0, 2, 1).reshape(
                    jobs * pf.A_SUB, pf.QLA)))
            r = np.sort(rng.integers(
                0, 1 << 63, size=jobs * span * rb, dtype=np.uint64))
            r_hi = jax.device_put(jnp.asarray(
                (r >> np.uint64(32)).astype(np.uint32).reshape(
                    jobs * span * pf.RSB, pf.B_LANE)))
            r_lo = jax.device_put(jnp.asarray(
                r.astype(np.uint32).reshape(
                    jobs * span * pf.RSB, pf.B_LANE)))

            def make_fn(reps):
                @jax.jit
                def run():
                    def body(_, acc):
                        a, b, c, d = jax.lax.optimization_barrier(
                            (q_hi, q_lo, r_hi, r_lo))
                        h = pf._window_hits(
                            a, b, c, d, span=span,
                            interpret=interpret)
                        return acc + jnp.sum(h, dtype=jnp.int32)
                    return jax.lax.fori_loop(
                        0, reps, body, jnp.int32(0), unroll=False)
                return lambda: int(np.asarray(run()))

            lo_hi = (1, 3) if interpret else (1, 6)
            per, disp, sus, ok = _measure_amortized(make_fn, *lo_hi)
            elems = jobs * qb
            results[label] = {
                "per_iter_ms": round(per * 1e3, 4),
                "dispatch_ms": round(disp * 1e3, 4),
                "elems_per_iter": elems,
                "elem_rate_per_s": round(elems / per, 1) if per else 0,
                "jobs": jobs, "span": span,
                "suspect": sus, "drift_ok": ok,
            }
            print(f"{label}: {per*1e3:.3f} ms/launch, "
                  f"dispatch {disp*1e3:.3f} ms", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"FAIL {label}: {type(e).__name__}: {e}", flush=True)
            results[label] = {"error": f"{type(e).__name__}: {e}"}

    # --- breakdown: host vs device split at the auto pack ---
    auto = results.get("pallas P=auto", {})
    kern = results.get("kernel amortized", {})
    breakdown = {}
    if auto.get("us_per_pair") is not None:
        breakdown["pallas_wall_us_per_pair"] = auto["us_per_pair"]
    if auto.get("fragment-pallas-launches") and kern.get("dispatch_ms"):
        breakdown["launch_overhead_us_per_pair"] = round(
            auto["fragment-pallas-launches"] * kern["dispatch_ms"]
            * 1e3 / len(pairs), 3)
    if auto.get("job_occupancy") is not None:
        breakdown["job_occupancy"] = auto["job_occupancy"]
    if auto.get("span_occupancy") is not None:
        breakdown["span_occupancy"] = auto["span_occupancy"]
    xla = results.get("xla vmapped", {})
    if xla.get("us_per_pair") and auto.get("us_per_pair"):
        breakdown["speedup_vs_xla"] = round(
            xla["us_per_pair"] / auto["us_per_pair"], 2)
    if breakdown:
        results["breakdown"] = breakdown
    if skipped:
        results["skipped"] = skipped

    print("FRAGMENT_JSON " + json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
