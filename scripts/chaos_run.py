#!/usr/bin/env python
"""Kill-anywhere chaos harness: prove preemption safety by killing runs.

Loop (seeded, deterministic given --seed):

  1. build a tiny synthetic-family FASTA workload and compute the
     uninterrupted reference clustering once;
  2. each iteration, launch the same clustering as a subprocess with a
     checkpoint dir and interrupt it a different way — SIGTERM at a
     random delay (the cooperative path: stop at a safe boundary, exit
     75), a GALAH_FI ``kill`` fault (os._exit mid-operation at a
     random dispatch or durable-write site — the SIGKILL/preemption
     stand-in), or a GALAH_FI filesystem fault (enospc / eio /
     torn-write inside io/atomic.py);
  3. resume with ``--resume`` (faults cleared) until the run completes;
  4. assert: the final cluster output is byte-identical to the
     uninterrupted reference, every artifact in the checkpoint and
     cache dirs is readable through the recovery-aware readers with no
     ``.tmp`` debris left in the (single-owner) checkpoint dir, and
     the final run_report.json records the interruption/resume chain.

Any violation prints the evidence and exits 1. The acceptance gate is
25 consecutive passing iterations (``--iterations 25``); the bounded
CI smoke (tests/test_chaos.py, ``pytest -m chaos``) drives the same
functions at ~10 iterations.

Usage:
    python scripts/chaos_run.py --iterations 25 [--seed 0] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from galah_tpu.fleet.plan import PLAN_FILENAME  # noqa: E402
from galah_tpu.io import atomic  # noqa: E402
from galah_tpu.resilience.faults import KILL_EXIT_CODE  # noqa: E402
from galah_tpu.resilience.interrupt import EXIT_PREEMPTED  # noqa: E402

#: The interruption modes one iteration draws from (round-robin with a
#: seeded shuffle, so 25 iterations cover every mode several times).
MODES = ("sigterm", "kill", "enospc", "eio", "torn-write")

RUN_TIMEOUT_S = 600


# ---------------------------------------------------------------------------
# Workload
# ---------------------------------------------------------------------------


def make_workload(root: str, seed: int, families: int = 2,
                  members: int = 3, length: int = 20_000) -> List[str]:
    """Synthetic genome families (test_synthetic_families.py recipe):
    `families` random bases, `members` genomes each at ~0.5%
    within-family divergence — small enough for seconds-scale CPU
    runs, structured enough that the clustering is non-trivial."""
    import numpy as np

    bases = np.array(list("ACGT"))
    rng = np.random.default_rng(seed)
    paths = []
    for fam in range(families):
        base = rng.integers(0, 4, size=length)
        for member in range(members):
            codes = base.copy()
            if member:
                sites = rng.random(length) < 0.005
                codes[sites] = (codes[sites] + rng.integers(
                    1, 4, size=int(sites.sum()))) % 4
            p = os.path.join(root, f"fam{fam}_m{member}.fna")
            seq = "".join(bases[codes])
            with open(p, "w") as f:
                f.write(">contig1\n")
                for i in range(0, len(seq), 70):
                    f.write(seq[i:i + 70] + "\n")
            paths.append(p)
    return paths


def cluster_argv(genomes: List[str], out_tsv: str, ckpt: str,
                 report: str, resume: bool,
                 precluster: str = "skani") -> List[str]:
    argv = [sys.executable, "-m", "galah_tpu.cli", "cluster",
            "--platform", "cpu",
            "--genome-fasta-files", *genomes,
            "--precluster-method", precluster,
            "--cluster-method", "skani",
            "--output-cluster-definition", out_tsv,
            "--checkpoint-dir", ckpt,
            "--run-report", report]
    if resume:
        argv.append("--resume")
    return argv


#: Env for the cluster-overlap workload: force the overlapped dataflow
#: (any engagement failure is then a loud error, not a silent demote)
#: and pin the XLA sketcher — single-device CPU hosts AUTO-resolve to
#: the C sketcher, whose sketches arrive as one batch rather than a
#: stream, which disengages the overlap. A resumed run reloads saved
#: distances and quietly runs stage-serial by design, so the same env
#: is safe on every launch in the kill/resume chain.
OVERLAP_ENV = {"GALAH_TPU_OVERLAP": "1",
               "GALAH_TPU_SKETCH_STRATEGY": "xla",
               "GALAH_TPU_GREEDY_STRATEGY": "device",
               # pinned, not auto: a fused-fold failure must fail the
               # iteration loudly instead of demoting to the dense
               # path and quietly passing the byte-identity gate
               "GALAH_TPU_MEGAKERNEL": "1"}

#: Env for the paged iterations the cluster-overlap workload
#: interleaves: the out-of-core sketch tier forced on (docs/memory.md)
#: with a 1 MiB resident budget, so every page-in evicts and the kill/
#: fault window covers the pagestore commit sites
#: (io.atomic.write[pagestore.page], io.atomic.append[pagestore.dir] —
#: prefix-matched by the harness's site=io.atomic fault spec).  The
#: paged band walk is a bucketed stage-serial pass — mutually
#: exclusive with the forced overlap (a stream cannot band a prefix) —
#: so these iterations leave GALAH_TPU_OVERLAP at auto and gate
#: against the SAME overlapped reference: the chaos loop doubles as a
#: cross-engine byte-identity check.
PAGED_ENV = {"GALAH_TPU_SKETCH_STRATEGY": "xla",
             "GALAH_TPU_GREEDY_STRATEGY": "device",
             "GALAH_TPU_MEGAKERNEL": "1",
             "GALAH_TPU_HLL_BUCKETS": "1",
             "GALAH_TPU_PAGESTORE": "1",
             "GALAH_TPU_SKETCH_RAM_MB": "1"}


def index_argv(index_dir: str, genomes: Optional[List[str]] = None,
               action: str = "insert", resume: bool = False,
               report: Optional[str] = None) -> List[str]:
    """`galah-tpu index` argv for the index-insert chaos workload.
    --batch 2 keeps several durable safe boundaries inside one insert
    so kills land between batches as well as inside them."""
    argv = [sys.executable, "-m", "galah_tpu.cli", "index",
            "--platform", "cpu", "--index-dir", index_dir]
    if report:
        argv += ["--run-report", report]
    argv.append(action)
    if genomes:
        argv += ["--genome-fasta-files", *genomes]
    if action == "insert":
        argv += ["--batch", "2"]
        if resume:
            argv.append("--resume")
    return argv


def launch(argv: List[str], extra_env: Optional[Dict[str, str]] = None
           ) -> subprocess.Popen:
    env = dict(os.environ)
    env.pop("GALAH_FI", None)  # each run decides its own faults
    env.setdefault("JAX_PLATFORMS", "cpu")
    # chaos runs double as the concurrency-sanitizer workload: every
    # child arms GalahSan so kills land mid-acquisition too
    env.setdefault("GALAH_SAN", "1")
    env.update(extra_env or {})
    return subprocess.Popen(argv, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)


# ---------------------------------------------------------------------------
# Artifact audit
# ---------------------------------------------------------------------------


def scan_artifacts(ckpt_dir: str) -> List[str]:
    """Corruption findings in a checkpoint dir AFTER a completed run
    ([] == clean). Readable-with-recovery is the contract: torn lines
    rejected by their checksum are expected debris of a kill, but
    anything the recovery-aware readers cannot read, and any ``.tmp``
    left in the single-owner checkpoint dir after a successful run
    (its open sweeps), is a violation."""
    problems: List[str] = []
    if not os.path.isdir(ckpt_dir):
        return problems
    for name in sorted(os.listdir(ckpt_dir)):
        p = os.path.join(ckpt_dir, name)
        if name.endswith(".tmp"):
            problems.append(f"leftover tmp debris: {p}")
        elif name.endswith(".jsonl"):
            try:
                atomic.read_jsonl(p)
            except Exception as exc:
                problems.append(f"unreadable jsonl {p}: {exc}")
        elif name.endswith(".json"):
            try:
                with open(p) as f:
                    json.load(f)
            except Exception as exc:
                problems.append(f"unparseable json {p}: {exc}")
        elif name.endswith(".npz"):
            try:
                import numpy as np

                with np.load(p) as z:
                    for member in z.files:
                        z[member]
            except Exception as exc:
                problems.append(f"unloadable npz {p}: {exc}")
    return problems


# ---------------------------------------------------------------------------
# One iteration
# ---------------------------------------------------------------------------


def fault_env(mode: str, seed: int) -> Optional[Dict[str, str]]:
    """The GALAH_FI spec for an interruption mode (None for sigterm).

    ``kill`` uses a low per-site probability over ALL sites so the
    seeded coin picks a different dispatch or durable-write operation
    each iteration; the fs faults target io/atomic.py and fire once."""
    if mode == "sigterm":
        return None
    if mode == "kill":
        return {"GALAH_FI":
                f"site=;kind=kill;prob=0.15;seed={seed};max=1"}
    return {"GALAH_FI": f"site=io.atomic;kind={mode};prob=0.5;"
                        f"seed={seed};max=1"}


def run_one(genomes: List[str], work: str, mode: str, seed: int,
            log: List[str], precluster: str = "skani",
            extra_env: Optional[Dict[str, str]] = None
            ) -> Tuple[bool, str]:
    """One kill/resume iteration; returns (ok, detail)."""
    rng = random.Random(f"chaos:{seed}:{mode}")
    ckpt = os.path.join(work, "ckpt")
    out_tsv = os.path.join(work, "clusters.tsv")
    report = os.path.join(work, "report.json")

    # -- interrupted run ------------------------------------------------
    env = dict(extra_env or {})
    env.update(fault_env(mode, seed) or {})
    proc = launch(cluster_argv(genomes, out_tsv, ckpt, report,
                               resume=False, precluster=precluster),
                  env)
    if mode == "sigterm":
        # the workload runs ~2-3 s end to end (measured on the CPU
        # backend); this window lands the signal mid-run most of the
        # time while still exercising the landed-after-exit edge
        time.sleep(rng.uniform(0.4, 2.2))
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
    try:
        stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return False, f"{mode}: interrupted run hung"
    rc = proc.returncode
    log.append(f"    interrupted run exited {rc}")
    interrupted = rc != 0
    # SIGTERM can land before the handlers install (default handler:
    # -15) or after the run finished (0): all are legitimate outcomes
    # of killing at an arbitrary instant.
    acceptable = {0, 1, EXIT_PREEMPTED, KILL_EXIT_CODE, -15,
                  -signal.SIGKILL}
    if rc not in acceptable:
        return False, (f"{mode}: unexpected exit {rc}\n"
                       + stdout.decode(errors="replace")[-2000:])

    # -- resume until complete (faults cleared) -------------------------
    for attempt in range(3):
        if not interrupted:
            break
        can_resume = os.path.exists(
            os.path.join(ckpt, "fingerprint.json"))
        proc = launch(cluster_argv(genomes, out_tsv, ckpt, report,
                                   resume=can_resume,
                                   precluster=precluster), extra_env)
        try:
            stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            return False, f"{mode}: resume run hung"
        log.append(f"    resume attempt {attempt} exited "
                   f"{proc.returncode} (resume={can_resume})")
        if proc.returncode == 0:
            break
        if attempt == 2:
            return False, (f"{mode}: resume never completed "
                           f"(last exit {proc.returncode})\n"
                           + stdout.decode(errors="replace")[-2000:])

    if not os.path.exists(out_tsv):
        return False, f"{mode}: completed run left no cluster output"
    return True, stdout.decode(errors="replace")


def check_report(report_path: str, ckpt: str, was_preempted: bool
                 ) -> Optional[str]:
    """The final run report must record the resume chain."""
    try:
        with open(report_path) as f:
            rep = json.load(f)
    except Exception as exc:
        return f"run report unreadable: {exc}"
    pre = rep.get("preemption")
    if not isinstance(pre, dict):
        return "run report has no preemption section"
    if pre.get("resumed_from") != ckpt:
        return (f"resumed_from={pre.get('resumed_from')!r}, "
                f"expected {ckpt!r}")
    if was_preempted and pre.get("prior_interruptions", 0) < 1:
        return ("cooperative preemption left no interruption record "
                f"(prior_interruptions={pre.get('prior_interruptions')})")
    san = rep.get("sanitizer")
    if isinstance(san, dict):
        for key in ("undeclared_acquisitions", "undeclared_edges",
                    "inversions", "races"):
            if san.get(key, 0):
                return f"sanitizer violation: {key}={san[key]}"
    return None


def run_iteration(genomes: List[str], reference: bytes, workdir: str,
                  mode: str, seed: int, precluster: str = "skani",
                  extra_env: Optional[Dict[str, str]] = None
                  ) -> Tuple[bool, str]:
    work = os.path.join(workdir, f"iter_{seed}_{mode}")
    os.makedirs(work, exist_ok=True)
    log: List[str] = []
    ok, detail = run_one(genomes, work, mode, seed, log,
                         precluster=precluster, extra_env=extra_env)
    if not ok:
        return False, "\n".join(log + [detail])
    ckpt = os.path.join(work, "ckpt")
    with open(os.path.join(work, "clusters.tsv"), "rb") as f:
        out = f.read()
    if out != reference:
        return False, "\n".join(log + [
            f"{mode}: resumed clusters differ from the uninterrupted "
            f"reference ({len(out)} vs {len(reference)} bytes)"])
    problems = scan_artifacts(ckpt)
    if problems:
        return False, "\n".join(log + [f"{mode}: corrupt artifacts:"]
                                + problems)
    was_preempted = "exited 75" in "\n".join(log)
    # the chain is only recorded when the completing run actually
    # resumed a durable checkpoint; a kill BEFORE the fingerprint ever
    # reached disk legitimately starts over with no chain to record
    resumed = any("resume=True" in line for line in log)
    if resumed:
        err = check_report(os.path.join(work, "report.json"), ckpt,
                           was_preempted)
        if err:
            return False, "\n".join(log + [f"{mode}: {err}"])
    return True, "\n".join(log)


# ---------------------------------------------------------------------------
# Index-insert workload
# ---------------------------------------------------------------------------


def index_dir_bytes(path: str) -> Dict[str, bytes]:
    """Byte snapshot of an index directory, keyed by file name.

    ``interruptions.jsonl`` is the one legitimately run-dependent file
    (it records the kills themselves); everything else — logs,
    generation manifests, commit pointer, fingerprint — must converge
    to the uninterrupted reference byte for byte."""
    out: Dict[str, bytes] = {}
    for name in sorted(os.listdir(path)):
        if name == "interruptions.jsonl":
            continue
        with open(os.path.join(path, name), "rb") as f:
            out[name] = f.read()
    return out


def run_index_iteration(base_idx: str, new_genomes: List[str],
                        reference: Dict[str, bytes], workdir: str,
                        mode: str, seed: int,
                        cache_env: Dict[str, str]) -> Tuple[bool, str]:
    """One kill/resume iteration over `index insert`; (ok, detail).

    Asserts the three index-insert chaos invariants: a kill at any
    instant leaves the index fsck-clean and loadable at a committed
    generation; a completed resume leaves zero .tmp debris; and the
    converged directory is byte-identical to the uninterrupted insert
    (modulo the interruption chain record)."""
    from galah_tpu.index import store as index_store

    work = os.path.join(workdir, f"ixiter_{seed}_{mode}")
    os.makedirs(work, exist_ok=True)
    idx = os.path.join(work, "idx")
    shutil.copytree(base_idx, idx)
    report = os.path.join(work, "report.json")
    log: List[str] = []
    rng = random.Random(f"chaos-index:{seed}:{mode}")

    env = dict(cache_env)
    env.update(fault_env(mode, seed) or {})
    proc = launch(index_argv(idx, new_genomes, report=report), env)
    if mode == "sigterm":
        # the insert runs ~2-3 s end to end on the CPU backend; this
        # window lands the signal mid-run most of the time while still
        # exercising the landed-after-exit edge
        time.sleep(rng.uniform(0.4, 2.2))
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
    try:
        stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return False, f"{mode}: interrupted insert hung"
    rc = proc.returncode
    log.append(f"    interrupted insert exited {rc}")
    interrupted = rc != 0
    acceptable = {0, 1, EXIT_PREEMPTED, KILL_EXIT_CODE, -15,
                  -signal.SIGKILL}
    if rc not in acceptable:
        return False, "\n".join(log + [
            f"{mode}: unexpected exit {rc}",
            stdout.decode(errors="replace")[-2000:]])

    # invariant 1: whatever instant the kill landed, the index is
    # loadable at a committed generation with zero fsck problems
    # (uncommitted tails and tmp debris are expected warnings here)
    rep = index_store.fsck(idx)
    if rep["problems"]:
        return False, "\n".join(log + [
            f"{mode}: fsck problems after the kill:"] + rep["problems"])
    if rep["generation"] not in (1, 2):
        return False, "\n".join(log + [
            f"{mode}: unexpected generation {rep['generation']} "
            f"after the kill"])
    log.append(f"    post-kill index loadable at generation "
               f"{rep['generation']}")

    for attempt in range(3):
        if not interrupted:
            break
        proc = launch(index_argv(idx, new_genomes, resume=True,
                                 report=report), cache_env)
        try:
            stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            return False, f"{mode}: resumed insert hung"
        log.append(f"    resume attempt {attempt} exited "
                   f"{proc.returncode}")
        if proc.returncode == 0:
            break
        if attempt == 2:
            return False, "\n".join(log + [
                f"{mode}: resumed insert never completed "
                f"(last exit {proc.returncode})",
                stdout.decode(errors="replace")[-2000:]])

    # invariant 2: a completed insert leaves no .tmp debris and every
    # artifact readable through the recovery-aware readers
    problems = scan_artifacts(idx)
    if problems:
        return False, "\n".join(log + [f"{mode}: corrupt artifacts:"]
                                + problems)
    rep = index_store.fsck(idx)
    if not rep["ok"]:
        return False, "\n".join(log + [f"{mode}: final fsck failed:"]
                                + rep["problems"] + rep["warnings"])

    # invariant 3: byte-identical convergence with the uninterrupted
    # reference insert
    got = index_dir_bytes(idx)
    if got != reference:
        diffs = sorted(set(got) ^ set(reference)) + [
            n for n in sorted(set(got) & set(reference))
            if got[n] != reference[n]]
        return False, "\n".join(log + [
            f"{mode}: converged index differs from the uninterrupted "
            f"reference in: {diffs}"])
    return True, "\n".join(log)


def run_index_harness(iterations: int, seed: int, workdir: str,
                      verbose: bool = True) -> int:
    """Chaos loop over `index insert`; returns FAILED iteration count.

    Builds the base index once (uninterrupted), computes the reference
    insert on a copy, then kills/resumes the same insert on fresh
    copies. The insert mixes joiners into existing clusters (each
    family's last member) with a whole novel family (new
    representatives), so kills land on both decision paths."""
    gdir = os.path.join(workdir, "genomes")
    os.makedirs(gdir, exist_ok=True)
    genomes = make_workload(gdir, seed, families=3, members=4,
                            length=12_000)
    new = [genomes[3], genomes[7]] + genomes[8:]
    base = [g for g in genomes if g not in new]
    cache_env = {"GALAH_TPU_CACHE":
                 os.path.join(workdir, "sketch_cache")}

    base_idx = os.path.join(workdir, "base_idx")
    proc = launch(index_argv(base_idx, base, action="build"), cache_env)
    stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
    if proc.returncode != 0:
        print("FATAL: index build failed:\n"
              + stdout.decode(errors="replace")[-3000:])
        return iterations or 1

    ref_idx = os.path.join(workdir, "ref_idx")
    shutil.copytree(base_idx, ref_idx)
    proc = launch(index_argv(ref_idx, new), cache_env)
    stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
    if proc.returncode != 0:
        print("FATAL: reference insert failed:\n"
              + stdout.decode(errors="replace")[-3000:])
        return iterations or 1
    reference = index_dir_bytes(ref_idx)
    if verbose:
        print(f"reference index: {len(reference)} files, "
              f"{sum(len(b) for b in reference.values())} bytes")

    rng = random.Random(seed)
    schedule = [MODES[i % len(MODES)] for i in range(iterations)]
    rng.shuffle(schedule)
    failures = 0
    for i, mode in enumerate(schedule):
        ok, detail = run_index_iteration(
            base_idx, new, reference, workdir, mode,
            seed * 1000 + i, cache_env)
        status = "PASS" if ok else "FAIL"
        if verbose or not ok:
            print(f"[{i + 1:2d}/{iterations}] index/{mode:<10s} "
                  f"{status}")
            for line in detail.splitlines():
                if not ok or line.strip().startswith(
                        ("interrupted", "resume", "post-kill")):
                    print(f"      {line.strip()}")
        failures += 0 if ok else 1
    print(f"chaos[index]: {iterations - failures}/{iterations} "
          f"iterations passed")
    return failures


# ---------------------------------------------------------------------------
# Fleet workload
# ---------------------------------------------------------------------------

#: Fleet interruption modes: SIGKILL a worker's whole process group
#: (the preempted-node stand-in), SIGKILL the SCHEDULER itself — its
#: workers survive in their own sessions and the resumed supervisor
#: must adopt and re-own them — or SIGTERM the scheduler (cooperative
#: drain: the signal is forwarded to every worker group, everyone
#: exits 75 at a safe boundary).
FLEET_MODES = ("worker-kill", "sched-kill", "sched-sigterm")

#: Chaos knobs for every fleet launch: a deep reassignment budget (a
#: kill/resume chain must never quarantine a healthy shard for being
#: unlucky), tight poll/heartbeat cadence so preemption detection fits
#: seconds-scale runs, and deterministic near-zero backoff.
FLEET_CHAOS_ENV = {
    "GALAH_TPU_FLEET_RETRY_MAX_ATTEMPTS": "10",
    "GALAH_TPU_FLEET_RETRY_BASE_DELAY": "0.05",
    "GALAH_TPU_FLEET_RETRY_MAX_DELAY": "0.2",
    "GALAH_TPU_FLEET_RETRY_JITTER": "0",
    "GALAH_TPU_FLEET_POLL_S": "0.1",
    "GALAH_TPU_FLEET_HEARTBEAT_S": "0.5",
}


def fleet_argv(genomes: List[str], fleet_dir: str, out_tsv: str,
               report: str, resume: bool, workers: int = 2,
               shards: int = 3) -> List[str]:
    argv = [sys.executable, "-m", "galah_tpu.cli", "fleet",
            "--platform", "cpu", "run",
            "--genome-fasta-files", *genomes,
            "--precluster-method", "skani",
            "--cluster-method", "skani",
            "--fleet-dir", fleet_dir,
            "--workers", str(workers),
            "--shards", str(shards),
            "--output-cluster-definition", out_tsv,
            "--run-report", report]
    if resume:
        argv.append("--resume")
    return argv


def find_fleet_workers(fleet_dir: str) -> List[int]:
    """Pids of live fleet WORKER processes (each a session leader,
    so pid == pgid), found by /proc cmdline: any galah_tpu process
    whose argv references the fleet's shards/ subtree is a worker —
    the scheduler references the fleet dir itself, never the
    subtree."""
    marker = os.path.join(fleet_dir, "shards") + os.sep
    pids: List[int] = []
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/cmdline", "rb") as f:
                cmdline = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        if marker in cmdline and "galah_tpu" in cmdline:
            pids.append(int(entry))
    return sorted(pids)


def check_fleet_report(report_path: str, n_shards: int
                       ) -> Optional[str]:
    """The completing run's report must carry a coherent fleet
    section: every shard done, every shard's lifetime launch count
    equal to its recorded preemption chain plus the one attempt that
    finished, and the fleet totals equal to the sum of the chains."""
    try:
        with open(report_path) as f:
            rep = json.load(f)
    except Exception as exc:
        return f"run report unreadable: {exc}"
    fleet = rep.get("fleet")
    if not isinstance(fleet, dict):
        return "run report has no fleet section"
    if (fleet.get("n_shards") != n_shards
            or fleet.get("shards_done") != n_shards):
        return (f"incomplete fleet: n_shards={fleet.get('n_shards')} "
                f"shards_done={fleet.get('shards_done')} "
                f"(expected {n_shards})")
    if fleet.get("shards_failed"):
        return f"quarantined shards: {fleet.get('shards_failed')}"
    chain_total = 0
    for sh in fleet.get("shards", []):
        chain = sh.get("preemptions", [])
        chain_total += len(chain)
        if sh.get("status") != "done":
            return (f"shard {sh.get('shard_id')} finished with "
                    f"status {sh.get('status')!r}")
        if sh.get("attempts", 0) != len(chain) + 1:
            return (f"incoherent chain for shard "
                    f"{sh.get('shard_id')}: {sh.get('attempts')} "
                    f"attempt(s) vs {len(chain)} preemption(s) "
                    f"{chain}")
    if fleet.get("preemptions") != chain_total:
        return (f"preemption total {fleet.get('preemptions')} != "
                f"sum of shard chains {chain_total}")
    if fleet.get("reassignments") != chain_total:
        return (f"reassignments {fleet.get('reassignments')} != "
                f"preemption total {chain_total}")
    san = rep.get("sanitizer")
    if isinstance(san, dict):
        for key in ("undeclared_acquisitions", "undeclared_edges",
                    "inversions", "races"):
            if san.get(key, 0):
                return f"sanitizer violation: {key}={san[key]}"
    return None


def check_fleet_analyze(fleet_dir: str) -> Optional[str]:
    """``galah-tpu fleet analyze --json`` must succeed on the
    completed fleet dir — even when the scheduler itself was killed
    mid-run, the event log alone must support a rollup — and its
    blame decomposition must conserve the fleet wall: component
    blame_s summing to fleet_wall_s within 1%, with a named
    bottleneck."""
    proc = subprocess.run(
        [sys.executable, "-m", "galah_tpu.cli", "fleet", "analyze",
         "--json", "--no-report", fleet_dir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=120)
    if proc.returncode != 0:
        return (f"fleet analyze exited {proc.returncode}: "
                + proc.stderr.decode(errors="replace")[-1000:])
    try:
        ru = json.loads(proc.stdout)
    except Exception as exc:
        return f"fleet analyze --json emitted unparseable JSON: {exc}"
    wall = ru.get("fleet_wall_s")
    comps = ru.get("components", {})
    if not isinstance(wall, (int, float)) or wall <= 0:
        return f"fleet analyze rollup has no wall: {wall!r}"
    blame_sum = sum(c.get("blame_s", 0.0) for c in comps.values()
                    if isinstance(c, dict))
    if abs(blame_sum - wall) > 0.01 * wall:
        return (f"fleet blame does not conserve the wall: "
                f"sum {blame_sum:.3f}s vs wall {wall:.3f}s")
    if not ru.get("bottleneck"):
        return "fleet analyze named no bottleneck"
    return None


def run_fleet_iteration(genomes: List[str], reference: bytes,
                        workdir: str, mode: str, seed: int,
                        cache_env: Dict[str, str], shards: int = 3
                        ) -> Tuple[bool, str]:
    """One fleet kill/resume iteration; returns (ok, detail)."""
    work = os.path.join(workdir, f"fliter_{seed}_{mode}")
    os.makedirs(work, exist_ok=True)
    fleet_dir = os.path.join(work, "fleet")
    out_tsv = os.path.join(work, "clusters.tsv")
    report = os.path.join(work, "report.json")
    log: List[str] = []
    rng = random.Random(f"chaos-fleet:{seed}:{mode}")
    env = dict(cache_env)
    env.update(FLEET_CHAOS_ENV)

    # -- interrupted fleet run ------------------------------------------
    proc = launch(fleet_argv(genomes, fleet_dir, out_tsv, report,
                             resume=False, shards=shards), env)
    if mode == "worker-kill":
        # wait for workers to appear, then SIGKILL one or two whole
        # worker process groups at seeded instants (a kill may land
        # mid-profile, mid-checkpoint-write, or after the worker
        # already finished — all must be survivable)
        want = rng.randint(1, 2)
        killed = 0
        deadline = time.monotonic() + 60
        while (killed < want and proc.poll() is None
               and time.monotonic() < deadline):
            time.sleep(rng.uniform(0.2, 0.9))
            workers = find_fleet_workers(fleet_dir)
            if not workers:
                continue
            victim = workers[rng.randrange(len(workers))]
            try:
                os.killpg(victim, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                continue
            killed += 1
            log.append(f"    SIGKILLed worker group {victim}")
    else:
        time.sleep(rng.uniform(1.0, 6.0))
        if proc.poll() is None:
            sig = (signal.SIGKILL if mode == "sched-kill"
                   else signal.SIGTERM)
            proc.send_signal(sig)
            log.append(f"    sent {sig.name} to the scheduler process")
    try:
        stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return False, "\n".join(
            log + [f"{mode}: interrupted fleet run hung"])
    rc = proc.returncode
    log.append(f"    interrupted fleet run exited {rc}")
    interrupted = rc != 0
    # no GALAH_FI faults here, so exit 1 (quarantine) is NOT
    # acceptable: the reassignment budget must absorb every kill
    acceptable = {0, EXIT_PREEMPTED, -15, -signal.SIGKILL}
    if rc not in acceptable:
        return False, "\n".join(log + [
            f"{mode}: unexpected exit {rc}",
            stdout.decode(errors="replace")[-2000:]])

    # -- resume until complete ------------------------------------------
    for attempt in range(3):
        if not interrupted:
            break
        can_resume = os.path.exists(
            os.path.join(fleet_dir, PLAN_FILENAME))
        proc = launch(fleet_argv(genomes, fleet_dir, out_tsv, report,
                                 resume=can_resume, shards=shards),
                      env)
        try:
            stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            return False, "\n".join(
                log + [f"{mode}: resumed fleet run hung"])
        log.append(f"    resume attempt {attempt} exited "
                   f"{proc.returncode} (resume={can_resume})")
        if proc.returncode == 0:
            break
        if attempt == 2:
            return False, "\n".join(log + [
                f"{mode}: fleet never completed "
                f"(last exit {proc.returncode})",
                stdout.decode(errors="replace")[-2000:]])

    # -- invariants -----------------------------------------------------
    if not os.path.exists(out_tsv):
        return False, "\n".join(
            log + [f"{mode}: completed fleet left no cluster output"])
    with open(out_tsv, "rb") as f:
        out = f.read()
    if out != reference:
        return False, "\n".join(log + [
            f"{mode}: fleet clusters differ from the single-process "
            f"reference ({len(out)} vs {len(reference)} bytes)"])
    problems = scan_artifacts(fleet_dir)
    shards_dir = os.path.join(fleet_dir, "shards")
    if os.path.isdir(shards_dir):
        for name in sorted(os.listdir(shards_dir)):
            sroot = os.path.join(shards_dir, name)
            problems += scan_artifacts(sroot)
            problems += scan_artifacts(os.path.join(sroot, "ckpt"))
    for dirpath, _dirnames, filenames in os.walk(fleet_dir):
        for fn in filenames:
            if fn.endswith(".tmp"):
                p = os.path.join(dirpath, fn)
                msg = f"leftover tmp debris: {p}"
                if msg not in problems:
                    problems.append(msg)
    if problems:
        return False, "\n".join(
            log + [f"{mode}: corrupt fleet artifacts:"] + problems)
    err = check_fleet_report(report, n_shards=shards)
    if err:
        return False, "\n".join(log + [f"{mode}: {err}"])
    err = check_fleet_analyze(fleet_dir)
    if err:
        return False, "\n".join(log + [f"{mode}: {err}"])
    return True, "\n".join(log)


def run_fleet_harness(iterations: int, seed: int, workdir: str,
                      verbose: bool = True) -> int:
    """Chaos loop over an elastic fleet run; returns FAILED count.

    The reference is the same corpus through ONE single-process
    ``cluster`` run. Every iteration runs ``fleet run`` sharded 3 ways
    across 2 workers — 10 genomes in 2 families, so the contiguous
    shard boundaries land MID-family and the cross-shard merge pairs
    are real — then SIGKILLs a worker group or the scheduler itself
    (round-robin over FLEET_MODES: any 3+ iterations kill the
    scheduler at least once), resumes, and holds the converged fleet
    to byte-identical output with zero debris and a coherent
    reassignment chain in the run report."""
    gdir = os.path.join(workdir, "genomes")
    os.makedirs(gdir, exist_ok=True)
    genomes = make_workload(gdir, seed, families=2, members=5,
                            length=12_000)
    cache_env = {"GALAH_TPU_CACHE":
                 os.path.join(workdir, "sketch_cache")}

    ref_work = os.path.join(workdir, "reference")
    os.makedirs(ref_work, exist_ok=True)
    ref_tsv = os.path.join(ref_work, "clusters.tsv")
    proc = launch(cluster_argv(
        genomes, ref_tsv, os.path.join(ref_work, "ckpt"),
        os.path.join(ref_work, "report.json"), resume=False),
        cache_env)
    stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
    if proc.returncode != 0:
        print("FATAL: reference run failed:\n"
              + stdout.decode(errors="replace")[-3000:])
        return iterations or 1
    with open(ref_tsv, "rb") as f:
        reference = f.read()
    if verbose:
        nlines = reference.count(b"\n")
        print(f"reference clustering: {len(reference)} bytes, "
              f"{nlines} lines")

    rng = random.Random(seed)
    schedule = [FLEET_MODES[i % len(FLEET_MODES)]
                for i in range(iterations)]
    rng.shuffle(schedule)
    failures = 0
    for i, mode in enumerate(schedule):
        ok, detail = run_fleet_iteration(
            genomes, reference, workdir, mode, seed * 1000 + i,
            cache_env)
        status = "PASS" if ok else "FAIL"
        if verbose or not ok:
            print(f"[{i + 1:2d}/{iterations}] fleet/{mode:<13s} "
                  f"{status}")
            for line in detail.splitlines():
                if not ok or line.strip().startswith(
                        ("interrupted", "resume", "SIGKILLed",
                         "sent")):
                    print(f"      {line.strip()}")
        failures += 0 if ok else 1
    print(f"chaos[fleet]: {iterations - failures}/{iterations} "
          f"iterations passed")
    return failures


# ---------------------------------------------------------------------------
# Harness
# ---------------------------------------------------------------------------


def run_harness(iterations: int, seed: int, workdir: str,
                verbose: bool = True, overlap: bool = False) -> int:
    """Full chaos loop; returns the number of FAILED iterations.

    With ``overlap=True`` every child run (reference, interrupted, and
    resume) uses the finch preclusterer with the overlapped dataflow
    forced on, so kills land inside the single fused pipeline — mid
    ingest, mid speculative fragment batch, or at the quiesce point —
    and the byte-identity gate proves the overlapped engine is exactly
    as preemption-safe as the stage-serial one.  Odd iterations swap
    in ``PAGED_ENV`` instead: the paged bucketed band walk forced on
    under a tiny resident budget, so the same kill/fault schedule also
    lands inside pagestore page commits and evictions — still gated
    byte-for-byte against the overlapped reference."""
    precluster = "finch" if overlap else "skani"
    extra_env = OVERLAP_ENV if overlap else None
    gdir = os.path.join(workdir, "genomes")
    os.makedirs(gdir, exist_ok=True)
    genomes = make_workload(gdir, seed)

    # uninterrupted reference
    ref_work = os.path.join(workdir, "reference")
    os.makedirs(ref_work, exist_ok=True)
    ref_tsv = os.path.join(ref_work, "clusters.tsv")
    proc = launch(cluster_argv(
        genomes, ref_tsv, os.path.join(ref_work, "ckpt"),
        os.path.join(ref_work, "report.json"), resume=False,
        precluster=precluster), extra_env)
    stdout, _ = proc.communicate(timeout=RUN_TIMEOUT_S)
    if proc.returncode != 0:
        print("FATAL: reference run failed:\n"
              + stdout.decode(errors="replace")[-3000:])
        return iterations or 1
    with open(ref_tsv, "rb") as f:
        reference = f.read()
    if verbose:
        nlines = reference.count(b"\n")
        print(f"reference clustering: {len(reference)} bytes, "
              f"{nlines} lines")

    rng = random.Random(seed)
    schedule = [MODES[i % len(MODES)] for i in range(iterations)]
    rng.shuffle(schedule)
    failures = 0
    for i, mode in enumerate(schedule):
        paged = overlap and i % 2 == 1
        ok, detail = run_iteration(genomes, reference, workdir, mode,
                                   seed * 1000 + i,
                                   precluster=precluster,
                                   extra_env=PAGED_ENV if paged
                                   else extra_env)
        status = "PASS" if ok else "FAIL"
        label = f"{mode}+paged" if paged else mode
        if verbose or not ok:
            print(f"[{i + 1:2d}/{iterations}] {label:<16s} {status}")
            if verbose or not ok:
                for line in detail.splitlines():
                    if not ok or line.strip().startswith(
                            ("interrupted", "resume")):
                        print(f"      {line.strip()}")
        failures += 0 if ok else 1
    print(f"chaos: {iterations - failures}/{iterations} iterations "
          f"passed")
    return failures


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--iterations", type=int, default=25)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    ap.add_argument("--workload", default="cluster",
                    choices=("cluster", "cluster-overlap",
                             "index-insert", "fleet"),
                    help="what to kill: a checkpointed cluster run "
                         "(default), the same run with the overlapped "
                         "dataflow forced on — odd iterations force "
                         "the paged sketch tier instead "
                         "(cluster-overlap), an "
                         "incremental `index insert` against a "
                         "prebuilt index, or an elastic multi-worker "
                         "`fleet run` whose workers AND scheduler get "
                         "killed (fleet)")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="galah_chaos_")
    print(f"chaos scratch: {workdir}")
    try:
        if args.workload == "index-insert":
            failures = run_index_harness(args.iterations, args.seed,
                                         workdir)
        elif args.workload == "fleet":
            failures = run_fleet_harness(args.iterations, args.seed,
                                         workdir)
        else:
            failures = run_harness(
                args.iterations, args.seed, workdir,
                overlap=args.workload == "cluster-overlap")
    finally:
        if not args.keep and not args.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
