#!/bin/bash
# Round-long watcher: restart tpu_validation_run.sh whenever it gives up
# (60 failed probes = ~2h window) so the tunnel is probed all round.
# A successful run leaves its captures in docs/artifacts/tpu_watch_*/ and
# a sentinel file so the builder notices and commits them.
set -u
LOG=/root/repo/scripts/tpu_validation.log
while true; do
  if bash /root/repo/scripts/tpu_validation_run.sh; then
    # A zero exit only means a probe attached; run_stage swallows stage
    # failures. Declare the capture done only if the bench stage itself
    # exited 0 — otherwise keep probing (the tunnel may have flapped).
    ART=$(ls -dt /root/repo/docs/artifacts/tpu_watch_* 2>/dev/null | head -1)
    if [ -n "$ART" ] && grep -q -- "--- exit 0" "$ART/bench.txt" 2>/dev/null; then
      touch /root/repo/scripts/TPU_CAPTURE_DONE
      echo "=== watch_loop: capture complete ($ART) $(date -u) ===" >> "$LOG"
      exit 0
    fi
    echo "=== watch_loop: probe attached but bench stage failed ($ART), re-probing $(date -u) ===" >> "$LOG"
  else
    echo "=== watch_loop: window exhausted, restarting $(date -u) ===" >> "$LOG"
  fi
  sleep 30
done
