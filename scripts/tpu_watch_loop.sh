#!/bin/bash
# Round-long watcher: restart tpu_validation_run.sh whenever it gives up
# (60 failed probes = ~2h window) so the tunnel is probed all round.
# A successful run leaves its captures in docs/artifacts/tpu_watch_*/ and
# a sentinel file so the builder notices and commits them.
set -u
LOG=/root/repo/scripts/tpu_validation.log
# Same single-client tunnel lock as tpu_validation_run.sh: the watcher
# takes it explicitly around each spawn (GALAH_TUNNEL_LOCKED=1 tells
# the child not to re-acquire) so a manually-launched validation run
# and a watcher-spawned one can never share the chip — the round-5
# 08:39 contention mode. -w 600: a manual session should finish its
# stage soon; if not, this iteration gives up and the loop re-probes.
LOCKFILE=${GALAH_TPU_TUNNEL_LOCK:-/tmp/galah_tpu_tunnel.lock}
while true; do
  if env GALAH_TUNNEL_LOCKED=1 flock -w 600 "$LOCKFILE" \
      bash /root/repo/scripts/tpu_validation_run.sh; then
    # A zero exit only means a probe attached; run_stage swallows stage
    # failures. Declare the capture done only if the bench stage itself
    # exited 0 — otherwise keep probing (the tunnel may have flapped).
    ART=$(ls -dt /root/repo/docs/artifacts/tpu_watch_* 2>/dev/null | head -1)
    if [ -n "$ART" ] && grep -q -- "--- exit 0" "$ART/bench.txt" 2>/dev/null; then
      touch /root/repo/scripts/TPU_CAPTURE_DONE
      echo "=== watch_loop: capture complete ($ART) $(date -u) ===" >> "$LOG"
      exit 0
    fi
    echo "=== watch_loop: probe attached but bench stage failed ($ART), re-probing $(date -u) ===" >> "$LOG"
  else
    echo "=== watch_loop: window exhausted, restarting $(date -u) ===" >> "$LOG"
  fi
  sleep 30
done
