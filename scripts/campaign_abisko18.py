"""Accuracy campaign: all backend combos over all 18 abisko4 MAGs.

Computes cluster compositions for every (precluster, cluster) method
combo at 95% and 99% ANI over the full abisko4 fixture set (the
reference's own tests use only 4-5 of these 18 MAGs), prints them, and
reports cross-combo agreement. Used once to derive the goldens pinned in
tests/test_campaign_abisko18.py; rerun after kernel changes to check for
drift.

Run on CPU mesh (default, deterministic) or TPU:
    python scripts/campaign_abisko18.py [--tpu]
"""

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if "--tpu" not in sys.argv:
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_ENABLE_X64", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")

from galah_tpu.api import generate_galah_clusterer  # noqa: E402

DATA = "/root/reference/tests/data/abisko4"

COMBOS = [
    ("finch", "skani"),
    ("finch", "fastani"),
    ("skani", "skani"),
    ("dashing", "skani"),
]


def run(paths, pre, cl, ani):
    values = {
        "ani": ani, "precluster_ani": 90.0,
        "min_aligned_fraction": 15.0, "fragment_length": 3000,
        "precluster_method": pre, "cluster_method": cl, "threads": 1,
        "checkm_tab_table": f"{DATA}/abisko4.csv",
        "quality_formula": "Parks2020_reduced",
    }
    clusterer = generate_galah_clusterer(list(paths), values)
    clusters = clusterer.cluster()
    names = [p.rsplit("/", 1)[1] for p in clusterer.genome_paths]
    return sorted(
        sorted(names[i] for i in cluster) for cluster in clusters)


def main():
    paths = sorted(glob.glob(f"{DATA}/*.fna"))
    assert len(paths) == 18, paths
    results = {}
    for ani in (95.0, 99.0):
        for pre, cl in COMBOS:
            t0 = time.perf_counter()
            comp = run(paths, pre, cl, ani)
            dt = time.perf_counter() - t0
            key = f"{pre}+{cl}@{ani:.0f}"
            results[key] = comp
            print(f"## {key}  ({dt:.1f}s, {len(comp)} clusters)")
            print(json.dumps(comp))
    # cross-combo agreement per threshold
    for ani in (95.0, 99.0):
        keys = [f"{p}+{c}@{ani:.0f}" for p, c in COMBOS]
        base = results[keys[0]]
        agree = [k for k in keys if results[k] == base]
        print(f"@{ani:.0f}: {len(agree)}/{len(keys)} combos agree "
              f"with {keys[0]}")


if __name__ == "__main__":
    main()
