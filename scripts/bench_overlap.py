"""Stage-serial vs fully overlapped end-to-end dataflow on the
e2e_1000 rung.

The overlapped engine (cluster/engine.py::_cluster_overlapped) fuses
sketch -> pair-screen -> speculative fragment-ANI -> eager greedy
rounds into one pipeline; this stage prices exactly that against the
stage-serial drain on the SAME workload the bench ladder's e2e_1000
rung runs (1000 synthetic genomes, 250 planted families x4, 3%
mutation, 100 kbp), end to end through
``generate_galah_clusterer(...).cluster()``:

  * overlapped: GALAH_TPU_OVERLAP=1, run FIRST so its jit compiles
    land inside its own timing (conservative for the speedup claim);
  * serial: GALAH_TPU_OVERLAP=0, the four-drain baseline;
  * parity: the two clusterings must be IDENTICAL — the overlap is a
    scheduling change, not an algorithm change, so a parity failure
    zeroes the speedup field and is reported.

Both runs pin GALAH_TPU_SKETCH_STRATEGY=xla (single-device CPU hosts
AUTO-resolve to the C sketcher, whose batch delivery disengages the
stream — the comparison must run the same sketcher either way) and
GALAH_TPU_GREEDY_STRATEGY=device (the overlap requires the round-based
device scan; pinning it for the serial run keeps the runs twins).

The payload carries the overlap counters (engaged / eager rounds /
speculative pairs and batches / demotions) and the per-stage
``workload.pipeline_occupancy[...]`` gauges for the overlapped run, so
a capture shows not just the rate but WHERE the pipeline sat busy vs
starved — on a 1-core host the wall-clock win is capped by the serial
CPU fraction and the occupancy split is the evidence of TPU-side
headroom.

Self-budgeting like the variant matrices: under a tight --budget the
workload downshifts to a 200-genome rung (recorded in `workload`), and
a partial run still prints OVERLAP_JSON with what it measured.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_T0 = time.monotonic()

# Overlap bookkeeping copied into the payload (deltas across the timed
# overlapped run).
_COUNTERS = ("overlap-engaged", "overlap-eager-rounds",
             "overlap-spec-pairs", "overlap-spec-batches",
             "overlap-demoted", "greedy-rounds",
             "greedy-host-fallback-windows")

_VALUES = {"ani": 95.0, "precluster_ani": 90.0,
           "min_aligned_fraction": 15.0, "fragment_length": 3000,
           "precluster_method": "finch", "cluster_method": "skani",
           "threads": 1}

# Pinned for BOTH runs — see the module docstring.
_PINS = {"GALAH_TPU_SKETCH_STRATEGY": "xla",
         "GALAH_TPU_GREEDY_STRATEGY": "device"}


def _left(budget):
    return budget - (time.monotonic() - _T0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=None,
                    help="seconds for the whole stage (default 570, "
                         "capped by GALAH_BENCH_STAGE_CAP)")
    args = ap.parse_args()

    budget = args.budget if args.budget is not None else 570.0
    cap = os.environ.get("GALAH_BENCH_STAGE_CAP")
    if cap:
        budget = min(budget, float(cap))

    from bench import _synth_families
    from galah_tpu.api import generate_galah_clusterer
    from galah_tpu.obs import flow as obs_flow
    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.utils import timing

    # The full rung costs ~2x the e2e wall (two complete runs); under
    # a tight budget downshift rather than print nothing.
    if _left(budget) >= 240:
        n_genomes, n_families = 1000, 250
    else:
        n_genomes, n_families = 200, 50
    paths = _synth_families(n_genomes=n_genomes, genome_len=100_000,
                            n_families=n_families, mut=0.03, seed=11)

    try:
        host_cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        host_cores = os.cpu_count() or 1

    out = {
        "workload": f"{n_genomes} synthetic genomes, {n_families} "
                    "planted families x4, 3% mutation, 100 kbp, "
                    "murmur3 finch+skani, xla sketcher",
        "n_genomes": n_genomes,
        # The overlap hides device time behind host stages; a 1-core
        # host has no spare core to overlap INTO, so speedup ~1x there
        # is the expected ceiling, not a regression — readers must
        # interpret `speedup` against this field.
        "host_cores": host_cores,
        "skipped": [],
    }
    clusterings = {}

    def run_one(mode):
        env_saved = {k: os.environ.get(k)
                     for k in ("GALAH_TPU_OVERLAP", *_PINS)}
        os.environ["GALAH_TPU_OVERLAP"] = \
            "1" if mode == "overlapped" else "0"
        os.environ.update(_PINS)
        obs_metrics.reset()  # per-run occupancy gauges
        obs_flow.reset()  # per-run flow graph
        try:
            before = timing.GLOBAL.counters()
            t0 = time.perf_counter()
            clusterer = generate_galah_clusterer(list(paths),
                                                 dict(_VALUES))
            clusters = clusterer.cluster()
            dt = time.perf_counter() - t0
            after = timing.GLOBAL.counters()
        finally:
            for k, v in env_saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        clusterings[mode] = clusters
        out[f"{mode}_genomes_per_sec"] = round(len(paths) / dt, 2)
        out[f"{mode}_seconds"] = round(dt, 3)
        out[f"{mode}_n_clusters"] = len(clusters)
        if mode == "overlapped":
            out["counters"] = {
                k: after.get(k, 0) - before.get(k, 0)
                for k in _COUNTERS
                if after.get(k, 0) - before.get(k, 0)}
            occ = {}
            for name, snap in obs_metrics.snapshot().items():
                if name.startswith("workload.pipeline_occupancy"):
                    stage = (name.split("[", 1)[1].rstrip("]")
                             if "[" in name else "pipeline")
                    occ[stage] = round(snap.get("value", 0.0), 3)
            out["occupancy"] = occ
            out["engaged"] = bool(
                out["counters"].get("overlap-engaged"))
            # critical-path blame shares over the overlapped wall —
            # which stage limits genomes/s (docs/observability.md)
            fsnap = obs_flow.snapshot()
            if fsnap.get("stages"):
                cp = obs_flow.critical_path(fsnap, dt)
                out["flow"] = {
                    "bottleneck": cp.get("bottleneck"),
                    "shares": {s: e["share"]
                               for s, e in cp["stages"].items()},
                }

    # Overlapped first: its compiles are billed to it.
    for mode in ("overlapped", "serial"):
        if _left(budget) < 30:
            out["skipped"].append(mode)
            continue
        try:
            run_one(mode)
        except Exception as e:  # noqa: BLE001 - partial JSON > crash
            out[f"{mode}_error"] = f"{type(e).__name__}: {e}"

    if "overlapped" in clusterings and "serial" in clusterings:
        out["parity"] = clusterings["overlapped"] == clusterings["serial"]
        if out["parity"] and out.get("serial_genomes_per_sec"):
            out["speedup"] = round(
                out["overlapped_genomes_per_sec"]
                / out["serial_genomes_per_sec"], 2)
            if host_cores <= 1:
                out["speedup_note"] = (
                    "1-core host: no spare core to overlap into, "
                    "speedup ~1x is the expected ceiling (parity is "
                    "the verdict here, not the rate)")
        elif not out["parity"]:
            out["speedup"] = 0.0

    print("OVERLAP_JSON " + json.dumps(out))


if __name__ == "__main__":
    main()
