"""Per-strategy screened-pair throughput + per-term cost breakdown.

The round-5 campaign measured the one-pair pairlist grid at 62.8k
pairs/s amortized (7.8% of the derived VPU ceiling) with NO analysis
of where the other 92% goes. This stage times every survivor-
evaluation strategy (ops/sparse_device.py) and decomposes the blocked
kernel's per-pair cost into named terms so a hardware negative is a
documented decision:

  * blocked P sweep (P = 1 is the retired round-5 grid): amortized
    on-chip pairs/s per bench_amortized's slope method;
  * xla: the vmapped u64-searchsorted fallback path;
  * gather-dense: wall-clock through ops/sparse_device's dense-tile
    strategy on a duplication-heavy (family-clique) and a low-dup
    pair list — includes host planning, so it is the rate a
    production run would see;
  * lo_only: the blocked kernel with the hi-plane compare halves
    dropped (WRONG integers, bench-only) — the same DMA traffic with
    ~1/3 of the compare work, pricing the u64-emulation tax.

Per-term model (per-pair microseconds, B pairs per dispatch):
    u(P) = c_pair + c_grid / P
  c_grid        = (u(1) - u(8)) * 8/7   -- per-program fixed cost
  u64_tax       = u_full(8) - u_lo(8)   -- extra compares for 64-bit
  dma_floor     = bytes_per_pair / HBM_BW (analytic, v5e ~8.1e11 B/s)
  u32_residual  = u(8) - c_grid/8 - u64_tax - dma_floor

Self-budgeting: variants run in priority order and each is admitted
only if its estimated cost fits the remaining budget (default 300 s;
GALAH_BENCH_STAGE_CAP caps it harder) — a partial run still prints
PAIRLIST_JSON with what it measured and what it skipped.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_amortized import (  # noqa: E402
    PAIR_CEILING,
    _measure_amortized,
    _row,
)

HBM_BW = 8.1e11  # bytes/s, v5e spec sheet (BASELINE.md roofline)
_T0 = time.monotonic()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interpret", action="store_true",
                    help="CPU smoke mode: tiny shapes, interpret=True")
    ap.add_argument("--budget", type=float, default=None,
                    help="seconds for the whole stage (default 300, "
                         "capped by GALAH_BENCH_STAGE_CAP)")
    args = ap.parse_args()

    budget = args.budget if args.budget is not None else 300.0
    cap = os.environ.get("GALAH_BENCH_STAGE_CAP")
    if cap:
        budget = min(budget, float(cap))

    import jax

    interpret = args.interpret
    if interpret:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from galah_tpu.ops.pairwise import _pair_stats
    from galah_tpu.ops.pallas_pairlist import pair_stats_pairs_pallas

    if not interpret:
        assert jax.default_backend() == "tpu", jax.default_backend()

    # Interpret mode is a wiring smoke, not a measurement: shrink both
    # the sketch width (compile cost scales with K_pad/8 static lane
    # loops) and the batch so the whole variant matrix fits the budget.
    K = 256 if interpret else 1000
    B = 64 if interpret else 8192
    rng = np.random.default_rng(1)
    results = {}
    skipped = []

    def left():
        return budget - (time.monotonic() - _T0)

    def admit(cost_s, label):
        if left() >= cost_s:
            return True
        skipped.append(label)
        print(f"SKIP {label}: needs ~{cost_s:.0f}s, "
              f"{left():.0f}s left", flush=True)
        return False

    n_pool = 256 if interpret else 1024
    pool = rng.integers(0, 1 << 63, size=(n_pool, K), dtype=np.uint64)
    pool.sort(axis=1)
    pa = jax.device_put(
        jnp.asarray(pool[rng.integers(0, n_pool, size=B)]))
    pb = jax.device_put(
        jnp.asarray(pool[rng.integers(0, n_pool, size=B)]))

    def make_blocked(block_pairs, lo_only=False):
        def make_fn(reps):
            @jax.jit
            def run():
                def body(_, acc):
                    aa, bb = jax.lax.optimization_barrier((pa, pb))
                    cm, tt = pair_stats_pairs_pallas(
                        aa, bb, K, interpret=interpret,
                        block_pairs=block_pairs, _lo_only=lo_only)
                    return acc + jnp.sum(cm, dtype=jnp.int32) \
                        + jnp.sum(tt, dtype=jnp.int32)
                return jax.lax.fori_loop(
                    0, reps, body, jnp.int32(0), unroll=False)
            return lambda: int(np.asarray(run()))
        return make_fn

    def make_xla(reps):
        @jax.jit
        def run():
            def body(_, acc):
                aa, bb = jax.lax.optimization_barrier((pa, pb))
                cm, tt = jax.vmap(
                    lambda a, b: _pair_stats(a, b, K))(aa, bb)
                return acc + jnp.sum(cm, dtype=jnp.int32) \
                    + jnp.sum(tt, dtype=jnp.int32)
            return jax.lax.fori_loop(
                0, reps, body, jnp.int32(0), unroll=False)
        return lambda: int(np.asarray(run()))

    lo_hi = (1, 3) if interpret else (1, 6)
    # Priority order: the tentpole A/B first (P=8 vs the retired P=1
    # grid gives the grid-overhead term), then the u64-tax probe, then
    # the fallback and the sweep tails, then the gather-dense regimes.
    # Cost estimates are per-variant admission guards; interpret mode
    # uses the shrunk shapes so its estimates shrink with them.
    c_blk, c_xla = (45, 20) if interpret else (60, 90)
    jobs = [(f"blocked P={p}", c_blk, make_blocked(p))
            for p in ((8, 1) if interpret else (8, 1, 4, 16))]
    jobs.insert(2, ("blocked P=8 lo_only", c_blk, make_blocked(8, True)))
    jobs.insert(3, ("xla vmapped", c_xla, make_xla))
    for label, cost, mk in jobs:
        if not admit(cost, label):
            continue
        try:
            per, disp, sus, ok = _measure_amortized(mk, *lo_hi)
            _row(label, B, per, disp, sus, ok, PAIR_CEILING, results)
        except Exception as e:  # noqa: BLE001 - record, keep going
            print(f"FAIL {label}: {type(e).__name__}: {e}", flush=True)
            results[label] = {"error": f"{type(e).__name__}: {e}"}

    # --- gather-dense strategy, wall-clock (host plan + tiles) ---
    from galah_tpu.ops.sparse_device import (
        _gather_dense_pair_stats,
        _plan_gather_segments,
    )

    n_rows = 128 if interpret else 1024
    jmat = jax.device_put(jnp.asarray(pool[:n_rows]))

    def gather_pairs(regime):
        if regime == "high-dup":   # family cliques: m-member all-pairs
            m, nfam = 32, (2 if interpret else 24)
            pi = np.concatenate([
                np.repeat(np.arange(m, dtype=np.int32) + f * m, m)
                for f in range(nfam)])
            pj = np.concatenate([
                np.tile(np.arange(m, dtype=np.int32) + f * m, m)
                for f in range(nfam)])
            keep = pi < pj
            return pi[keep], pj[keep]
        n_p = 256 if interpret else 8192   # low-dup: scattered pairs
        pi = rng.integers(0, n_rows - 1, size=n_p).astype(np.int32)
        pj = np.minimum(pi + 1 + rng.integers(0, 64, size=n_p),
                        n_rows - 1).astype(np.int32)
        return pi, pj

    c_gather = 30 if interpret else 90
    for regime in ("high-dup", "low-dup"):
        label = f"gather-dense {regime}"
        if not admit(c_gather, label):
            continue
        try:
            pi, pj = gather_pairs(regime)
            order = np.lexsort((pj, pi))
            _, cells = _plan_gather_segments(pi[order], pj[order])
            got = _gather_dense_pair_stats(
                jmat, pi, pj, K, interpret, explicit=True)
            t0 = time.perf_counter()
            got = _gather_dense_pair_stats(
                jmat, pi, pj, K, interpret, explicit=True)
            dt = time.perf_counter() - t0
            rate = pi.shape[0] / dt if dt > 0 else 0.0
            fill = pi.shape[0] / max(cells, 1)
            print(f"{label}: {rate:,.0f} pairs/s wall "
                  f"(fill {fill:.3f}, {pi.shape[0]} pairs, "
                  f"{cells} cells)", flush=True)
            results[label] = {
                "rate_per_s": round(rate, 1),
                "fill": round(fill, 4),
                "n_pairs": int(pi.shape[0]),
                "tile_cells": int(cells),
                "pct_of_ceiling": round(100.0 * rate / PAIR_CEILING, 2),
            }
        except Exception as e:  # noqa: BLE001
            print(f"FAIL {label}: {type(e).__name__}: {e}", flush=True)
            results[label] = {"error": f"{type(e).__name__}: {e}"}

    # --- per-term breakdown from the measured rows ---
    def u(label):
        r = results.get(label, {})
        per = r.get("per_iter_ms")
        return per * 1e3 / B if per else None   # us/pair

    u8, u1, ulo = u("blocked P=8"), u("blocked P=1"), \
        u("blocked P=8 lo_only")
    k_pad = 1024
    bytes_per_pair = 2 * (k_pad * 8) + 2 * (8 * 128 * 4)
    breakdown = {"model": "u(P) = c_pair + c_grid/P; us per pair",
                 "bytes_per_pair": bytes_per_pair,
                 "dma_floor_us": round(bytes_per_pair / HBM_BW * 1e6,
                                       4)}
    if u8 is not None and u1 is not None:
        breakdown["grid_overhead_us"] = round((u1 - u8) * 8.0 / 7.0, 3)
    if u8 is not None and ulo is not None:
        breakdown["u64_tax_us"] = round(u8 - ulo, 3)
    if all(k in breakdown for k in ("grid_overhead_us", "u64_tax_us")):
        breakdown["u32_residual_us"] = round(
            u8 - breakdown["grid_overhead_us"] / 8.0
            - breakdown["u64_tax_us"] - breakdown["dma_floor_us"], 3)
    r8 = results.get("blocked P=8", {})
    if r8.get("dispatch_ms") is not None:
        breakdown["dispatch_ms"] = r8["dispatch_ms"]
    results["breakdown"] = breakdown
    if skipped:
        results["skipped"] = skipped

    print("PAIRLIST_JSON " + json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
