"""Host-vs-device greedy-selection throughput on the e2e_1000 rung.

The round-based device strategy (ops/greedy_select.py) replaces the
host path's one-dispatch-group-per-precluster greedy scan with K-wide
speculative rounds resolved in a jitted window fold. This stage prices
exactly that trade on the SAME workload the bench ladder's e2e_1000
rung runs (1000 synthetic genomes, 250 planted families x4, 3%
mutation, 100 kbp, default finch+skani), end to end through
``generate_galah_clusterer(...).cluster()``:

  * device: GALAH_TPU_GREEDY_STRATEGY=device, run FIRST so its jit
    compiles land inside its own timing (conservative for the speedup
    claim — the host run inherits any shared backend-kernel compiles);
  * host: GALAH_TPU_GREEDY_STRATEGY=host, the exact per-precluster
    scan that produced the r05 ladder rate (65.3 genomes/s);
  * parity: the two clusterings must be IDENTICAL (same nested index
    lists, reps first) — a speedup over a different answer is a bug,
    so a parity failure zeroes the speedup field and is reported.

The payload carries the round/conflict/fallback counter deltas for the
device run so a capture shows not just the rate but how the rounds
went (how many windows fell back to the exact host-order scan).

Self-budgeting like the variant matrices: under a tight --budget the
workload downshifts to a 200-genome rung (recorded in `workload`), and
a partial run still prints ENGINE_ROUNDS_JSON with what it measured.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_T0 = time.monotonic()

# Device-round bookkeeping copied into the payload (deltas across the
# timed device run).
_COUNTERS = ("greedy-rounds", "greedy-subrounds",
             "greedy-conflict-windows", "greedy-host-fallback-windows",
             "greedy-replayed-pairs", "greedy-device-demoted")

_VALUES = {"ani": 95.0, "precluster_ani": 90.0,
           "min_aligned_fraction": 15.0, "fragment_length": 3000,
           "precluster_method": "finch", "cluster_method": "skani",
           "threads": 1}


def _left(budget):
    return budget - (time.monotonic() - _T0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=None,
                    help="seconds for the whole stage (default 570, "
                         "capped by GALAH_BENCH_STAGE_CAP)")
    args = ap.parse_args()

    budget = args.budget if args.budget is not None else 570.0
    cap = os.environ.get("GALAH_BENCH_STAGE_CAP")
    if cap:
        budget = min(budget, float(cap))

    from bench import _synth_families
    from galah_tpu.api import generate_galah_clusterer
    from galah_tpu.utils import timing

    # The full rung costs ~2x the host e2e wall (two complete runs);
    # under a tight budget downshift rather than print nothing.
    if _left(budget) >= 240:
        n_genomes, n_families = 1000, 250
    else:
        n_genomes, n_families = 200, 50
    paths = _synth_families(n_genomes=n_genomes, genome_len=100_000,
                            n_families=n_families, mut=0.03, seed=11)

    out = {
        "workload": f"{n_genomes} synthetic genomes, {n_families} "
                    "planted families x4, 3% mutation, 100 kbp, "
                    "default murmur3 finch+skani",
        "n_genomes": n_genomes,
        "skipped": [],
    }
    clusterings = {}

    def run_one(strategy):
        os.environ["GALAH_TPU_GREEDY_STRATEGY"] = strategy
        try:
            before = timing.GLOBAL.counters()
            t0 = time.perf_counter()
            clusterer = generate_galah_clusterer(list(paths),
                                                 dict(_VALUES))
            clusters = clusterer.cluster()
            dt = time.perf_counter() - t0
            after = timing.GLOBAL.counters()
        finally:
            del os.environ["GALAH_TPU_GREEDY_STRATEGY"]
        clusterings[strategy] = clusters
        out[f"{strategy}_genomes_per_sec"] = round(len(paths) / dt, 2)
        out[f"{strategy}_seconds"] = round(dt, 3)
        out[f"{strategy}_n_clusters"] = len(clusters)
        if strategy == "device":
            out["counters"] = {
                k: after.get(k, 0) - before.get(k, 0)
                for k in _COUNTERS if after.get(k, 0) - before.get(
                    k, 0)}

    # Device first: its window-fold jit compiles are billed to it.
    for strategy in ("device", "host"):
        if _left(budget) < 30:
            out["skipped"].append(strategy)
            continue
        try:
            run_one(strategy)
        except Exception as e:  # noqa: BLE001 - partial JSON > crash
            out[f"{strategy}_error"] = f"{type(e).__name__}: {e}"

    if "device" in clusterings and "host" in clusterings:
        out["parity"] = clusterings["device"] == clusterings["host"]
        if out["parity"] and out.get("host_genomes_per_sec"):
            out["speedup"] = round(
                out["device_genomes_per_sec"]
                / out["host_genomes_per_sec"], 2)
        elif not out["parity"]:
            out["speedup"] = 0.0

    print("ENGINE_ROUNDS_JSON " + json.dumps(out))


if __name__ == "__main__":
    main()
